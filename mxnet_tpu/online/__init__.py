"""Online learning loop (round 18): continuously-updating trainer ->
v2 ``.mxje`` export -> zero-downtime rolling swap, supervised for a
fault-proof sample-to-served freshness SLO.

* :class:`OnlineTrainer` — subprocess worker: deterministic replay
  stream through the data plane, cursor-bearing checkpoints, stamped
  artifact exports, atomic publish manifests.
* :class:`OnlineLoop` — supervisor: heals trainer deaths
  (relaunch + sample-exact resume), swaps each published version into
  a :class:`~mxnet_tpu.serving.FleetRouter` fleet, sheds superseded
  versions loudly, tracks freshness per commit.
* :class:`FreshnessTracker` — p50/p99 + SLO verdicts over committed
  swaps, fault-free-window filtering for the gate.

Knobs: ``MXNET_ONLINE_EXPORT_STEPS``, ``MXNET_FRESHNESS_SLO_MS``.
"""
from .freshness import FreshnessTracker  # noqa: F401
from .loop import OnlineLoop, OnlineTrainer, stream_batch  # noqa: F401

__all__ = ["OnlineLoop", "OnlineTrainer", "FreshnessTracker",
           "stream_batch"]
