"""Online learning loop: continuously-updating trainer -> export ->
rolling-swap, supervised for fault-proof freshness.

Two halves, one file:

* :class:`OnlineTrainer` — the WORKER half.  Runs in its own process
  (``python -m mxnet_tpu.online.loop --dir D ...``) so the supervisor
  can heal a SIGKILL or faultsim crash without dying itself.  It
  consumes a deterministic replay/live stream through the data plane
  (:class:`~mxnet_tpu.io.DeviceFeedIter` double-buffering), trains a
  gluon net step by step, and every ``MXNET_ONLINE_EXPORT_STEPS``
  steps (a) checkpoints params + stream cursor through
  :class:`~mxnet_tpu.resilience.checkpoint.CheckpointManager`, (b)
  exports a v2 ``.mxje`` artifact stamped (``extra_meta``) with the
  monotonic ``model_version`` and the ``stream_cursor`` /
  ``t_newest_sample`` it was trained through, and (c) publishes an
  atomic per-version JSON manifest the supervisor watches.  The
  ordering is load-bearing: **checkpoint first, artifact second,
  manifest last** — a death at any point leaves either nothing or a
  resumable prefix, and a version number can never be re-issued for
  different params (``allocate_version`` scans the checkpoint dir).

* :class:`OnlineLoop` — the SUPERVISOR half.  Spawns/relaunches the
  trainer (healable exits: signals, peer-death 83, faultsim 87 —
  the :mod:`~mxnet_tpu.resilience.healing` convention, with
  ``MXNET_HEAL_ATTEMPT`` exported and the fault spec scrubbed on
  relaunch), watches the publish dir, and rolling-swaps each new
  version into a :class:`~mxnet_tpu.serving.FleetRouter` fleet with
  zero downtime.  When the trainer outruns the swap pipeline the
  supervisor swaps only the NEWEST pending version and **sheds** the
  older ones loudly (``online_swaps_shed`` counter + ``swap_shed``
  freshness records) — freshness is about serving the newest model,
  not about serving every model.  Every committed swap records one
  sample-to-served freshness measurement
  (:class:`~mxnet_tpu.online.freshness.FreshnessTracker`); the first
  commit after a relaunch is marked fault-tainted so the SLO gate
  judges steady-state windows.

Robustness contract (drilled in ``tests/test_online.py`` and the
``trainer_death_midstream`` / ``swap_rollback`` chaos scenarios):

* trainer death mid-stream is healed via the cursor-bearing
  checkpoint; the resumed run replays the exact remaining samples
  (the stream is a pure function of ``(seed, cursor)``) so the final
  params are bit-identical to an uninterrupted run, and swaps never
  stall while the trainer is down;
* a failed swap rolls back (``FleetRouter.rolling_swap``) leaving
  every host on ONE version, and the router's ``model_version`` stamp
  check refuses any swap that would regress below the last committed
  version;
* sample-to-served freshness is tracked per commit and p99-gated in
  ``tools/benchdiff.py`` (``freshness`` bench phase).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as onp

from ..base import MXNetError
from ..resilience import faultsim
from ..telemetry import tracing as _tracing

__all__ = ["OnlineTrainer", "OnlineLoop", "stream_batch"]

faultsim.register_point(
    "online.step", "one online-trainer step (crash = trainer death "
    "mid-stream, healed by the OnlineLoop supervisor)")
faultsim.register_point(
    "online.publish", "the atomic publish-manifest write (crash = "
    "death between artifact and manifest: the version stays invisible "
    "and is never half-served)")


# ------------------------------------------------------------ the stream
def stream_batch(seed, cursor, batch, features):
    """Batch ``cursor`` of the replay stream: a PURE function of
    ``(seed, cursor)`` — that determinism IS the sample-exact resume
    contract (replaying from a checkpointed cursor reproduces the
    exact remaining samples; no buffered tail to lose).  A linear
    teacher keyed by ``seed`` makes the loss trajectory meaningful."""
    rng = onp.random.RandomState((seed * 1000003 + cursor) % (2**31 - 1))
    x = rng.uniform(-1.0, 1.0, size=(batch, features)).astype("float32")
    w = onp.random.RandomState(seed).uniform(
        -1.0, 1.0, size=(features, 1)).astype("float32")
    y = x @ w
    return x, y


def _stream(seed, start, batch, features):
    cursor = int(start)
    while True:
        x, y = stream_batch(seed, cursor, batch, features)
        yield [x, y]
        cursor += 1


# -------------------------------------------------------------- trainer
class OnlineTrainer:
    """Worker half of the online loop (see module docstring).

    ``run()`` trains ``steps`` total steps — *total*, not additional:
    a relaunch resumes from the newest checkpoint's cursor and trains
    only the remainder.  ``pace_s`` stretches the loop so drills can
    land kills/swaps between export boundaries.
    """

    def __init__(self, workdir, *, steps=60, export_every=None, seed=7,
                 batch=8, features=4, lr=0.05, pace_s=0.0,
                 device_feed=True, keep_n=None):
        from ..config import get_env

        self.workdir = os.fspath(workdir)
        self.steps = int(steps)
        self.export_every = int(get_env("MXNET_ONLINE_EXPORT_STEPS")
                                if export_every is None else export_every)
        if self.export_every <= 0:
            raise MXNetError("export_every must be >= 1")
        self.seed = int(seed)
        self.batch = int(batch)
        self.features = int(features)
        self.lr = float(lr)
        self.pace_s = float(pace_s)
        self.device_feed = bool(device_feed)
        self.publish_dir = os.path.join(self.workdir, "publish")
        os.makedirs(self.publish_dir, exist_ok=True)
        ckpt_dir = os.path.join(self.workdir, "ckpt")
        os.makedirs(ckpt_dir, exist_ok=True)
        from ..resilience.checkpoint import CheckpointManager

        self.ckpt = CheckpointManager(os.path.join(ckpt_dir, "online"),
                                      keep_n=keep_n)
        self.pidfile = os.path.join(self.workdir, "trainer.pid")
        self.final_path = os.path.join(self.workdir, "final.json")

    # ------------------------------------------------------------- net
    def _build(self):
        import mxnet_tpu as mx
        from .. import gluon, nd

        mx.random.seed(self.seed)
        net = gluon.nn.Dense(1, in_units=self.features,
                             prefix="online_dense_")
        net.initialize(init=mx.init.Xavier())
        net(nd.zeros((1, self.features)))  # resolve shapes
        # explicit seeded init: run-to-run identity (and therefore the
        # sample-exact-resume comparison) must not depend on any
        # process-global RNG stream another subsystem may have advanced
        rng = onp.random.RandomState(self.seed + 1)
        net.weight.set_data(nd.array(rng.uniform(
            -0.5, 0.5, size=(1, self.features)).astype("float32")))
        net.bias.set_data(nd.zeros((1,)))
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": self.lr})
        return net, trainer

    @staticmethod
    def _canon(name):
        """Canonical param name: strip the gluon block-scope prefix
        (``dense0_weight`` -> ``weight``) so checkpoints and final
        params compare across processes regardless of how many blocks
        the process happened to name before ours."""
        return name.split("_", 1)[1] if "_" in name else name

    def _params(self, net):
        return {self._canon(k): p.data()
                for k, p in net.collect_params().items()}

    def _resume(self, net):
        """Restore params + cursor from the newest good checkpoint.
        Returns the step already completed (0 = fresh start)."""
        if self.ckpt.latest_epoch() is None:
            return 0
        state = self.ckpt.load()
        arg = state["arg_params"]
        for k, p in net.collect_params().items():
            ck = self._canon(k)
            if ck in arg:
                p.set_data(arg[ck])
        return int(state["step"] or 0)

    # ---------------------------------------------------------- export
    def _export(self, net, step, cursor, t_newest):
        """checkpoint -> artifact -> manifest, in that order (see
        module docstring for why the order is load-bearing)."""
        from .. import deploy, nd
        from ..resilience.checkpoint import atomic_write_bytes

        v = self.ckpt.allocate_version()
        extra = {"stream_cursor": int(cursor),
                 "t_newest_sample": float(t_newest),
                 "model_version": int(v)}
        # the trace anchor rides the artifact header + manifest so a
        # rolling-swap's serve spans link back to the trainer step that
        # produced the weights (tracemerge draws the arrow)
        ctx = t_exp0 = None
        if _tracing.enabled():
            parent = _tracing.current_context()
            ctx = parent.child() if parent is not None else _tracing.mint()
            extra["trace_anchor"] = ctx.to_header()
            t_exp0 = time.perf_counter()
        self.ckpt.save(v, arg_params=self._params(net), step=int(step),
                       batch_cursor=int(cursor), extra=extra)
        path = os.path.join(self.publish_dir, f"model-v{v:04d}.mxje")
        deploy.export_model(net, nd.zeros((self.batch, self.features)),
                            path, platforms=("cpu",), extra_meta=extra)
        man = dict(extra, path=path, step=int(step),
                   t_published=time.time())
        atomic_write_bytes(
            os.path.join(self.publish_dir, f"v{v:04d}.json"),
            (json.dumps(man, sort_keys=True) + "\n").encode(),
            inject_point="online.publish")
        if ctx is not None:
            _tracing.emit_span("online_export", t_exp0,
                               time.perf_counter(), ctx,
                               model_version=int(v), step=int(step))
        return v

    # ------------------------------------------------------------- run
    def run(self):
        """Train to ``steps``, exporting every ``export_every`` steps
        and at the end; returns ``{step, cursor, versions, params}``
        (also written atomically to ``final.json`` for cross-process
        sample-exactness checks)."""
        from .. import autograd, gluon
        from ..resilience.checkpoint import atomic_write_bytes

        with open(self.pidfile, "w") as f:
            f.write(str(os.getpid()))
        net, trainer = self._build()
        done = self._resume(net)
        loss_fn = gluon.loss.L2Loss()
        src = _stream(self.seed, done, self.batch, self.features)
        if self.device_feed:
            from ..io.device_feed import DeviceFeedIter

            src = DeviceFeedIter(src, depth=2)
        it = iter(src)
        versions = []
        cursor, t_newest = done, time.time()
        for step in range(done + 1, self.steps + 1):
            faultsim.inject("online.step")
            # each stream cursor is a trace entry point: the step span
            # (rooted on the supervisor's spawn stamp when present)
            # parents the export span, whose anchor the swap inherits
            with _tracing.span("online_step", cursor=int(step)):
                xb, yb = next(it)
                t_newest = time.time()
                with autograd.record():
                    loss = loss_fn(net(xb), yb)
                loss.backward()
                trainer.step(self.batch)
                cursor = step
                if step % self.export_every == 0 or step == self.steps:
                    versions.append(
                        self._export(net, step, cursor, t_newest))
            if self.pace_s:
                time.sleep(self.pace_s)
        final = {"step": int(cursor), "cursor": int(cursor),
                 "versions": [int(v) for v in versions],
                 "attempt": int(os.environ.get("MXNET_HEAL_ATTEMPT",
                                               "0")),
                 "params": {k: onp.asarray(v.asnumpy(),
                                           dtype="float64").ravel()
                            .tolist()
                            for k, v in self._params(net).items()}}
        atomic_write_bytes(self.final_path,
                           (json.dumps(final, sort_keys=True)
                            + "\n").encode(), inject_point=None)
        return final


# ----------------------------------------------------------- supervisor
def _healable(rc):
    """The healing convention: signals (negative), peer-death 83,
    faultsim crash 87."""
    from ..resilience import healing

    return (rc < 0 or rc == healing.PEER_DEATH_EXIT_CODE
            or rc == faultsim.CRASH_EXIT_CODE)


class OnlineLoop:
    """Supervisor half of the online loop (see module docstring).

    ``run()`` blocks until the trainer finishes and every published
    version is swapped or shed, then returns the report dict.  Live
    progress is visible on the instance (``served_versions``,
    ``relaunches``, ``shed``, ``proc``) so drills can act mid-run —
    e.g. SIGKILL the trainer after the first committed swap.
    """

    def __init__(self, workdir, router, *, model=None, steps=60,
                 export_every=None, seed=7, batch=8, features=4,
                 lr=0.05, pace_s=0.0, slo_ms=None, max_relaunch=3,
                 probe_timeout=120.0, poll_s=0.05, worker_env=None):
        from .freshness import FreshnessTracker

        self.workdir = os.fspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.router = router
        self.model = model
        self.steps = int(steps)
        self.export_every = export_every
        self.seed = int(seed)
        self.batch = int(batch)
        self.features = int(features)
        self.lr = float(lr)
        self.pace_s = float(pace_s)
        self.max_relaunch = int(max_relaunch)
        self.probe_timeout = float(probe_timeout)
        self.poll_s = float(poll_s)
        self.worker_env = dict(worker_env or {})
        self.publish_dir = os.path.join(self.workdir, "publish")
        self.pidfile = os.path.join(self.workdir, "trainer.pid")
        self.tracker = FreshnessTracker(slo_ms)
        self.served_versions = []
        self.shed = []
        self.rollbacks = 0
        self.last_rollback = None
        self.relaunches = 0
        self.proc = None
        self._seen = set()
        self._tainted = False  # next commit carries healing latency
        self._retry = None     # (version, manifest, tries) after rollback
        self._retry_after = 0.0
        self.max_swap_retries = 5

    # ---------------------------------------------------------- worker
    def _worker_cmd(self):
        cmd = [sys.executable, "-m", "mxnet_tpu.online.loop",
               "--dir", self.workdir, "--steps", str(self.steps),
               "--seed", str(self.seed), "--batch", str(self.batch),
               "--features", str(self.features), "--lr", str(self.lr),
               "--pace-s", str(self.pace_s)]
        if self.export_every is not None:
            cmd += ["--export-every", str(self.export_every)]
        return cmd

    def _spawn(self, attempt):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in [os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                env.get("PYTHONPATH")] if p)
        # the supervisor's own telemetry sink must not be shared with
        # the child (one-run-per-file contract)
        env.pop("MXNET_RUNLOG", None)
        # trace + identity stamp: the trainer's step spans parent onto
        # this supervisor's context (before worker_env so drills can
        # override)
        _tracing.stamp_env(env, "trainer", rank=attempt)
        env.update(self.worker_env)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["MXNET_HEAL_ATTEMPT"] = str(attempt)
        if attempt:
            # the chaos convention: an armed one-shot fault must not
            # re-fire on the healed attempt
            env.pop("MXNET_FAULT_SPEC", None)
        # the worker's stdout (its final-state JSON line) must not
        # interleave with the supervisor's own stdout contract (bench
        # emits ONE JSON line); keep it per-attempt for post-mortems
        log = open(os.path.join(self.workdir,
                                f"trainer.a{attempt}.log"), "wb")
        try:
            self.proc = subprocess.Popen(self._worker_cmd(), env=env,
                                         stdout=log,
                                         stderr=subprocess.STDOUT)
        finally:
            log.close()  # the child holds its own fd
        return self.proc

    # --------------------------------------------------------- publish
    def _pending(self):
        """New publish manifests, version-sorted: ``[(v, man), ...]``.
        A manifest is atomic (written last by the trainer), so seeing
        it means artifact + checkpoint are durable."""
        out = []
        try:
            names = os.listdir(self.publish_dir)
        except OSError:
            return out
        for name in sorted(names):
            if not (name.startswith("v") and name.endswith(".json")):
                continue
            try:
                v = int(name[1:-5])
            except ValueError:
                continue
            if v in self._seen:
                continue
            try:
                with open(os.path.join(self.publish_dir, name)) as f:
                    man = json.load(f)
            except (OSError, ValueError):
                continue  # racing a writer that is not atomic-renamed
            out.append((v, man))
        return out

    # ------------------------------------------------------------ swap
    def _swap(self, version, man):
        """Returns ``"committed"``, ``"rollback"`` (retryable) or
        ``"refused"`` (version-regression guard; shed, not retried)."""
        from .. import telemetry

        try:
            res = self.router.rolling_swap(
                man["path"], model=self.model,
                probe_timeout=self.probe_timeout)
        except MXNetError:
            # the router's no-regression guard refused it — shed, loud
            self._shed(version, reason="refused")
            return "refused"
        if not res.get("committed"):
            self.rollbacks += 1
            self.last_rollback = dict(res, version=int(version))
            telemetry.freshness("swap_rollback", version=version,
                                errors=res.get("errors"))
            return "rollback"
        t_commit = time.time()
        ms = max(0.0,
                 (t_commit - float(man["t_newest_sample"])) * 1000.0)
        fault_free = not self._tainted
        ok = self.tracker.record(version, ms, fault_free=fault_free)
        self._tainted = False
        self.served_versions.append(int(version))
        telemetry.count("online_swaps")
        telemetry.freshness("swap_commit", version=version,
                            freshness_ms=ms,
                            stream_cursor=man.get("stream_cursor"),
                            fault_free=fault_free)
        if not ok:
            telemetry.count("freshness_violations")
            telemetry.freshness("violation", version=version,
                                freshness_ms=ms)
        return "committed"

    def _shed(self, version, reason="superseded"):
        from .. import telemetry

        self.shed.append(int(version))
        telemetry.count("online_swaps_shed")
        telemetry.freshness("swap_shed", version=version, reason=reason)

    def _drain_publishes(self):
        """Swap the newest pending version; shed the rest (freshness
        wants the newest model serving, not every model served).  A
        rolled-back swap is RETRIED (bounded, paced) until it commits
        or a newer version supersedes it — swaps must not stall on a
        transient probe failure, and must not spin on a permanent
        one."""
        from .. import telemetry

        pending = self._pending()
        for v, _ in pending:
            self._seen.add(v)
            telemetry.count("online_exports")
            telemetry.freshness("publish", version=v)
        tries = 0
        if pending:
            newest_v, newest_man = pending[-1]
            for v, _ in pending[:-1]:
                self._shed(v)
            if self._retry is not None:
                self._shed(self._retry[0], reason="superseded")
            self._retry = None
        elif self._retry is not None:
            if time.monotonic() < self._retry_after:
                return
            newest_v, newest_man, tries = self._retry
            self._retry = None
        else:
            return
        if self._swap(newest_v, newest_man) == "rollback":
            if tries + 1 >= self.max_swap_retries:
                self._shed(newest_v, reason="rollback_budget")
            else:
                self._retry = (newest_v, newest_man, tries + 1)
                self._retry_after = time.monotonic() + 0.25

    @property
    def _swap_backlog(self):
        return self._retry is not None

    # ------------------------------------------------------------- run
    def run(self, timeout=600.0):
        from .. import telemetry

        deadline = time.monotonic() + float(timeout)
        self._spawn(0)
        worker_rc = None
        while True:
            if time.monotonic() > deadline:
                self.proc.kill()
                raise MXNetError(
                    f"online loop timed out after {timeout}s "
                    f"(served={self.served_versions})")
            self._drain_publishes()
            rc = self.proc.poll()
            if rc is not None:
                if rc == 0:
                    worker_rc = 0
                    break
                if (_healable(rc)
                        and self.relaunches < self.max_relaunch):
                    self.relaunches += 1
                    self._tainted = True
                    telemetry.count("online_relaunches")
                    telemetry.freshness("relaunch", rc=rc,
                                        attempt=self.relaunches)
                    self._spawn(self.relaunches)
                else:
                    raise MXNetError(
                        f"online trainer died rc={rc} "
                        f"(relaunches={self.relaunches}/"
                        f"{self.max_relaunch}) — not healable")
            time.sleep(self.poll_s)
        # the final exports land after the worker exits; keep draining
        # until nothing is pending and no rolled-back swap awaits retry
        while True:
            self._drain_publishes()
            if not self._pending() and not self._swap_backlog:
                break
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"online loop timed out draining publishes "
                    f"(served={self.served_versions})")
            time.sleep(self.poll_s)
        return self.report(worker_rc)

    def report(self, worker_rc=None):
        return {"steps": self.steps,
                "worker_rc": worker_rc,
                "relaunches": int(self.relaunches),
                "exports_seen": len(self._seen),
                "swaps": len(self.served_versions),
                "served_versions": list(self.served_versions),
                "swaps_shed": len(self.shed),
                "shed_versions": list(self.shed),
                "swap_rollbacks": int(self.rollbacks),
                "monotonic": all(
                    b >= a for a, b in zip(self.served_versions,
                                           self.served_versions[1:])),
                "freshness": self.tracker.report()}


# -------------------------------------------------------------- worker CLI
def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="online-trainer worker (spawned by OnlineLoop)")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--export-every", type=int, default=None)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--features", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--pace-s", type=float, default=0.0)
    args = ap.parse_args(argv)
    attempt = int(os.environ.get("MXNET_HEAL_ATTEMPT", "0"))
    if attempt:
        os.environ.pop("MXNET_FAULT_SPEC", None)
        faultsim.reset("")
    trainer = OnlineTrainer(
        args.dir, steps=args.steps, export_every=args.export_every,
        seed=args.seed, batch=args.batch, features=args.features,
        lr=args.lr, pace_s=args.pace_s)
    final = trainer.run()
    print(json.dumps({"final_step": final["step"],
                      "versions": final["versions"],
                      "attempt": attempt}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
