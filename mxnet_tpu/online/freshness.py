"""Freshness-SLO accounting for the online learning loop.

**Freshness** of a served model is the *sample-to-served* latency: the
age of the newest stream sample the model was trained on, measured at
the instant the serving fleet COMMITS the rolling swap::

    freshness_ms = (t_swap_commit - t_newest_sample) * 1e3

It bounds how stale the fleet's answers can be relative to the live
stream — the quantity an online-learning deployment actually promises
(``MXNET_FRESHNESS_SLO_MS``), as opposed to export cadence or swap
latency which are only its ingredients.

:class:`FreshnessTracker` collects one sample per committed swap and
answers the two questions the SLO gate asks:

* **p50/p99 over all samples** — the raw distribution, violations
  counted loudly against the SLO;
* **p99 over fault-free windows only** — a swap that lands right after
  a trainer crash/heal carries the healing latency by construction;
  the supervisor marks it ``fault_free=False`` and the gate excludes
  it, so the SLO judges the steady state while the tainted samples
  stay visible in the report (excluded, not hidden).

Percentiles use :func:`mxnet_tpu.telemetry.opstats.percentile`
(nearest-rank) so bench, opperf and the freshness gate share one rank
convention.
"""
from __future__ import annotations

__all__ = ["FreshnessTracker"]


class FreshnessTracker:
    """Per-swap freshness samples + SLO verdicts.

    ``slo_ms`` defaults to the ``MXNET_FRESHNESS_SLO_MS`` knob.  Each
    :meth:`record` returns whether THAT sample met the SLO and bumps
    ``violations`` when it did not; :meth:`report` folds the samples
    into the dict the bench ``freshness`` phase and the online drill
    assert on.
    """

    def __init__(self, slo_ms=None):
        if slo_ms is None:
            from ..config import get_env

            slo_ms = get_env("MXNET_FRESHNESS_SLO_MS")
        self.slo_ms = float(slo_ms)
        self._samples = []  # (version, freshness_ms, fault_free)
        self.violations = 0

    def record(self, version, freshness_ms, fault_free=True):
        """Record one committed swap; returns True when within SLO."""
        ms = float(freshness_ms)
        self._samples.append((int(version), ms, bool(fault_free)))
        ok = ms <= self.slo_ms
        if not ok:
            self.violations += 1
        return ok

    def __len__(self):
        return len(self._samples)

    @property
    def versions(self):
        return [v for v, _, _ in self._samples]

    @property
    def monotonic(self):
        """Served versions never went backwards (the one-identity /
        no-regression contract, as seen from the commit stream)."""
        vs = self.versions
        return all(b >= a for a, b in zip(vs, vs[1:]))

    @staticmethod
    def _stats(vals):
        from ..telemetry.opstats import percentile

        s = sorted(vals)
        return {"count": len(s),
                "p50_ms": round(percentile(s, 0.50), 3),
                "p99_ms": round(percentile(s, 0.99), 3)}

    def report(self):
        all_ms = [ms for _, ms, _ in self._samples]
        clean = [ms for _, ms, ff in self._samples if ff]
        clean_stats = self._stats(clean)
        # vacuously met with zero clean samples: an all-tainted run has
        # no steady state to judge (the drill separately requires >=1)
        clean_stats["within_slo"] = (not clean or
                                     clean_stats["p99_ms"] <= self.slo_ms)
        return {"slo_ms": self.slo_ms,
                "violations": int(self.violations),
                "monotonic": self.monotonic,
                "versions": self.versions,
                "all": self._stats(all_ms),
                "fault_free": clean_stats}
