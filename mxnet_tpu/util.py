"""np-semantics switches and misc utilities.

Reference parity: python/mxnet/util.py (set_np/use_np decorators switching
numpy-shape/array semantics, imperative.h:114 ``Imperative::is_np_shape``).
In this build the numpy namespace (mx.np) is always numpy-semantic; the
flags exist for API compatibility and gate only zero-dim shape handling.
"""
from __future__ import annotations

import functools
import threading


class _NpState(threading.local):
    def __init__(self):
        self.shape = False
        self.array = False


_NP = _NpState()


def set_np(shape=True, array=True):
    _NP.shape, _NP.array = shape, array


def reset_np():
    set_np(False, False)


def is_np_shape():
    return _NP.shape


def is_np_array():
    return _NP.array


class np_shape:
    def __init__(self, active=True):
        self.active = active

    def __enter__(self):
        self.prev = _NP.shape
        _NP.shape = self.active
        return self

    def __exit__(self, *exc):
        _NP.shape = self.prev


class np_array:
    def __init__(self, active=True):
        self.active = active

    def __enter__(self):
        self.prev = _NP.array
        _NP.array = self.active
        return self

    def __exit__(self, *exc):
        _NP.array = self.prev


def use_np(func):
    """Decorator: run `func` under np semantics (reference util.py use_np)."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True), np_array(True):
            return func(*args, **kwargs)

    return wrapper


def use_np_shape(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func(*args, **kwargs)

    return wrapper


def get_gpu_count():
    from .context import num_gpus

    return num_gpus()
