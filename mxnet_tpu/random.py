"""``mx.random`` — global seeding + module-level samplers.

Reference parity: python/mxnet/random.py (seed routed to per-device
generators via MXRandomSeedContext); here a single JAX key chain (_rng.py).
"""
from __future__ import annotations

from ._rng import seed  # noqa: F401
from .ndarray.random import (  # noqa: F401
    exponential,
    gamma,
    generalized_negative_binomial,
    multinomial,
    negative_binomial,
    normal,
    normal_like,
    poisson,
    randint,
    randn,
    shuffle,
    uniform,
    uniform_like,
)
