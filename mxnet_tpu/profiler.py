"""Profiler with the reference API, emitting Chrome-trace JSON.

Reference parity: python/mxnet/profiler.py:33-151 (set_config /
set_state / dump / dumps / pause / resume) and the user-scope objects
Domain/Task/Frame/Event/Counter/Marker (:225-497), backed in the
reference by the C++ Profiler with lock-free per-thread stat buffers
(src/profiler/profiler.h:251) dumped as Chrome tracing JSON
(src/profiler/aggregate_stats.cc).

TPU-native design: there is no engine thread pool to instrument — ops
dispatch asynchronously into the XLA runtime.  The profiler therefore
records two complementary layers:

  * host-side events — every ``nd`` op dispatch (the analog of the
    reference's per-op ProfileOperator begin/end), user scopes
    (Task/Frame/Event), counters and instant markers — buffered
    in-process and dumped as a Chrome trace (``chrome://tracing`` /
    Perfetto).
  * device-side tracing — ``jax.profiler`` XPlane capture for
    TensorBoard, toggled by the same set_state('run'/'stop') when
    ``set_config(profile_device=True, tensorboard_logdir=...)``.

Aggregate statistics (``dumps(format='table')``) mirror the reference's
aggregate_stats table: per-op call counts and total/min/max/mean host
dispatch time.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time

from .base import MXNetError

__all__ = [
    "set_config", "profiler_set_config", "set_state", "profiler_set_state",
    "dump", "dump_profile", "dumps", "pause", "resume", "op_scope",
    "now_us", "run_generation", "record_span", "record_counter",
    "record_instant", "record_meta", "events_snapshot",
    "Domain", "Task", "Frame", "Event", "Counter", "Marker",
]

_lock = threading.Lock()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": False,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": True,
    "aggregate_stats": False,
    "continuous_dump": False,
    "dump_period": 1.0,
    "profile_device": False,
    "tensorboard_logdir": None,
}
_state = "stop"
_paused = False
_events = []  # chrome trace event dicts
_agg = {}  # name -> [count, total_us, min_us, max_us]
_jax_trace_active = False
_run_gen = 0  # run-window starts; external lanes key metadata off it
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def now_us():
    """Microseconds on the profiler's trace clock — external lanes
    (telemetry.RunLog) must timestamp on THIS clock so their spans line
    up with the op events in one Perfetto timeline."""
    return _now_us()


def run_generation():
    """Counts run-window starts.  Lane owners (telemetry) key their
    per-trace metadata ('thread_name') off this so a second run window
    after a finished dump gets its lane named again, not skipped."""
    return _run_gen


def is_running():
    return _state == "run" and not _paused


def set_config(**kwargs):
    """Reference: profiler.py:33 — configure before set_state('run').

    Accepted kwargs mirror the reference (filename, profile_all,
    profile_symbolic, profile_imperative, profile_memory, profile_api,
    aggregate_stats, continuous_dump, dump_period) plus the TPU
    extensions profile_device / tensorboard_logdir.
    """
    if _state == "run":
        # reference parity (profiler.py:33 backed by the C++ check):
        # reconfiguring mid-collection (e.g. switching `filename`)
        # would silently split/lose events — refuse, like the C side
        raise MXNetError(
            "profiler.set_config cannot be called while the profiler "
            "is running; set_state('stop') first")
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError(f"unknown profiler config keys: {sorted(unknown)}")
    if kwargs.get("profile_all"):
        _config.update(profile_symbolic=True, profile_imperative=True,
                       profile_memory=True, profile_api=True)
    _config.update(kwargs)


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Deprecated reference alias (profiler.py:70)."""
    set_config(profile_symbolic=(mode in ("symbolic", "all")),
               profile_imperative=(mode in ("imperative", "all")),
               filename=filename)


def set_state(state="stop", profile_process="worker"):
    """Reference: profiler.py:89 — 'run' starts collection, 'stop' ends.

    Stopping with continuous_dump set dumps automatically (the reference
    dumps from the C++ side on WorkerProfile teardown).
    """
    global _state, _paused, _jax_trace_active, _run_gen
    if state not in ("run", "stop"):
        raise MXNetError(f"invalid profiler state {state!r}")
    prev = _state
    _state = state
    _paused = False
    if state == "run" and prev != "run":
        _run_gen += 1
        _record_instant("profiler_start", "profiler")
        if _config["profile_device"] and not _jax_trace_active:
            import jax

            logdir = _config["tensorboard_logdir"] or "/tmp/mxnet_tpu_trace"
            jax.profiler.start_trace(logdir)
            _jax_trace_active = True
    elif state == "stop" and prev == "run":
        if _jax_trace_active:
            import jax

            jax.profiler.stop_trace()
            _jax_trace_active = False
        if _config["continuous_dump"]:
            dump()


def profiler_set_state(state="stop"):
    """Deprecated reference alias (profiler.py:109)."""
    set_state(state)


def pause(profile_process="worker"):
    """Reference: profiler.py:193."""
    global _paused
    _paused = True


def resume(profile_process="worker"):
    """Reference: profiler.py:209."""
    global _paused
    _paused = False


def _record(name, cat, ph, ts_us, dur_us=None, args=None, tid=None):
    ev = {
        "name": name, "cat": cat, "ph": ph, "ts": ts_us,
        "pid": os.getpid(),
        "tid": tid if tid is not None else threading.get_ident() % 100000,
    }
    if dur_us is not None:
        ev["dur"] = dur_us
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)


def _record_instant(name, cat, args=None):
    _record(name, cat, "i", _now_us(), args=args)


def record_op(name, dur_us, cat="operator", args=None):
    """Record one complete op-dispatch event (internal hook; the analog
    of the reference's ProfileOperator, src/profiler/profiler.h:77)."""
    _record(name, cat, "X", _now_us() - dur_us, dur_us, args=args)
    if _config["aggregate_stats"]:
        with _lock:
            ent = _agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
            ent[0] += 1
            ent[1] += dur_us
            ent[2] = min(ent[2], dur_us)
            ent[3] = max(ent[3], dur_us)


def events_snapshot():
    """A copy of the buffered Chrome-trace events collected so far.

    The public hook the aggregate-opstats layer
    (:mod:`mxnet_tpu.telemetry.opstats`) folds per-op tables from:
    unlike :func:`dump`, it neither drains the buffer nor stops
    collection, so a mid-run aggregate costs one list copy."""
    with _lock:
        return list(_events)


def record_span(name, cat, start_us, dur_us, args=None, tid=None):
    """Public lane hook: one complete 'X' span on the trace clock
    (``now_us``).  Used by telemetry.RunLog to put step/feed-wait/
    checkpoint spans on the same Perfetto timeline as the op events.
    Respects the run/pause window like every other event."""
    if is_running():
        _record(name, cat, "X", start_us, dur_us, args=args, tid=tid)


def record_counter(name, value, cat="counter", tid=None):
    """Public lane hook: one 'C' counter sample (throughput, loss)."""
    if is_running():
        _record(name, cat, "C", _now_us(), args={name: value}, tid=tid)


def record_instant(name, cat, args=None, tid=None):
    """Public lane hook: one instant event."""
    if is_running():
        _record(name, cat, "i", _now_us(), args=args, tid=tid)


def record_meta(name, args, tid=None):
    """Metadata event ('M') — names a tid lane in Perfetto.  Not gated
    on is_running: lane names must land even when emitted just before
    the run window opens."""
    _record(name, "__metadata", "M", 0, args=args, tid=tid)


def op_scope(name):
    """Public dispatcher hook: a context manager timing one op dispatch,
    or None when op profiling is off (the hot-path fast exit)."""
    if is_running() and _config["profile_imperative"]:
        return _OpScope(name)
    return None


class _OpScope:
    """Context manager used by the nd dispatcher to time op dispatch."""

    __slots__ = ("name", "_start", "_bytes")

    def __init__(self, name):
        self.name = name
        self._bytes = None

    def set_result(self, out):
        """Attach the output size so the aggregate opstats table can
        report bytes per op; only ever paid while profiling is on."""
        total = 0
        outs = out if isinstance(out, (list, tuple)) else (out,)
        for o in outs:
            data = getattr(o, "_data", o)
            n = getattr(data, "nbytes", None)
            if n is not None:
                total += int(n)
        self._bytes = total or None

    def __enter__(self):
        self._start = _now_us()
        return self

    def __exit__(self, *exc):
        args = {"bytes": self._bytes} if self._bytes is not None \
            else None
        record_op(self.name, _now_us() - self._start, args=args)
        return False


def dump(finished=True, profile_process="worker"):
    """Reference: profiler.py:122 — write the Chrome trace JSON file.

    ``finished=True`` means profiling is COMPLETE: the buffer is
    flushed and collection stops (reference semantics — the C++ side
    tears down WorkerProfile).  ``finished=False`` writes a snapshot
    of everything collected so far and KEEPS collecting — the buffer
    is retained so the next dump carries the full timeline (periodic
    mid-run dumps watch a live training job without truncating it)."""
    global _state, _paused
    path = _config["filename"]
    with _lock:
        events = list(_events)
        if finished:
            _events.clear()
    if finished and _state == "run":
        global _jax_trace_active
        _state = "stop"
        _paused = False
        if _jax_trace_active:
            import jax

            jax.profiler.stop_trace()
            _jax_trace_active = False
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


def dump_profile():
    """Deprecated reference alias (profiler.py:143)."""
    dump(True)


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Reference: profiler.py:151 — return aggregate stats as a string.

    Requires set_config(aggregate_stats=True).  sort_by in
    {'total','avg','min','max','count'}.
    """
    if format not in ("table", "json"):
        raise MXNetError(f"invalid format {format!r}")
    key_idx = {"count": 0, "total": 1, "min": 2, "max": 3, "avg": 4}
    if sort_by not in key_idx:
        raise MXNetError(f"invalid sort_by {sort_by!r}")
    with _lock:
        rows = [
            (name, c, tot, mn if c else 0.0, mx, (tot / c) if c else 0.0)
            for name, (c, tot, mn, mx) in _agg.items()
        ]
        if reset:
            _agg.clear()
    rows.sort(key=lambda r: r[1 + key_idx[sort_by]], reverse=not ascending)
    if format == "json":
        return json.dumps([
            {"name": n, "count": c, "total_us": t, "min_us": mn,
             "max_us": mx, "avg_us": av} for n, c, t, mn, mx, av in rows])
    lines = [f"{'Name':<40s}{'Calls':>8s}{'Total(us)':>14s}"
             f"{'Min(us)':>12s}{'Max(us)':>12s}{'Avg(us)':>12s}"]
    for n, c, t, mn, mx, av in rows:
        lines.append(f"{n:<40.40s}{c:>8d}{t:>14.1f}{mn:>12.1f}"
                     f"{mx:>12.1f}{av:>12.1f}")
    return "\n".join(lines)


# ------------------------------------------------------------ user scopes
class Domain:
    """Reference: profiler.py:225 — namespace for user scope objects."""

    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    _cat = "user"

    def __init__(self, domain, name):
        self.name = name
        self.domain = domain
        self._start_ts = None

    def start(self):
        self._start_ts = _now_us()

    def stop(self):
        if self._start_ts is None:
            return
        if is_running():  # user scopes respect the run/pause window too
            dur = _now_us() - self._start_ts
            cat = f"{self._cat}:{self.domain}" if self.domain \
                else self._cat
            _record(self.name, cat, "X", self._start_ts, dur)
        self._start_ts = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass

    def __str__(self):
        return self.name


class Task(_Span):
    """Reference: profiler.py:284."""

    _cat = "task"


class Frame(_Span):
    """Reference: profiler.py:326."""

    _cat = "frame"


class Event(_Span):
    """Reference: profiler.py:368 (domain-less event)."""

    _cat = "event"

    def __init__(self, name):
        super().__init__(None, name)


class Counter:
    """Reference: profiler.py:404 — emits Chrome 'C' counter samples."""

    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if is_running():
            _record(self.name, f"counter:{self.domain}", "C", _now_us(),
                    args={self.name: value})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self

    def __str__(self):
        return str(self._value)


class Marker:
    """Reference: profiler.py:474 — instant event."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if is_running():
            _record(self.name, f"marker:{self.domain}", "i", _now_us(),
                    args={"scope": scope})


@atexit.register
def _shutdown():
    global _jax_trace_active
    if _jax_trace_active:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        _jax_trace_active = False
