"""mx.image — image I/O, transforms, augmenters, and ImageIter.

Reference parity: python/mxnet/image/image.py (2,504 LoC —
imread/imdecode/imresize/*crop*, the Aug class chain built by
CreateAugmenter :1025, and the pure-Python ImageIter :1139).

TPU-native notes: decoded images are HWC uint8/float numpy-backed
NDArrays (host memory — decode/augment is host work that feeds
device_put); the heavy decode path can ride the native C++ extension
(mxnet_tpu._native) with PIL as fallback.
"""
from __future__ import annotations

import io as _io
import logging
import os
import random as pyrandom

import numpy as onp

from .. import ndarray as nd
from .. import recordio
from ..base import MXNetError
from ..io.io import DataBatch, DataIter

__all__ = [
    "imread", "imdecode", "imresize", "resize_short", "fixed_crop",
    "center_crop", "random_crop", "random_size_crop", "color_normalize",
    "copyMakeBorder", "Augmenter", "SequentialAug", "RandomOrderAug",
    "ResizeAug", "ForceResizeAug", "RandomCropAug", "CenterCropAug",
    "RandomSizedCropAug", "HorizontalFlipAug", "CastAug",
    "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
    "HueJitterAug", "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
    "RandomGrayAug", "CreateAugmenter", "ImageIter",
]


def _pil():
    from PIL import Image

    return Image


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to an HWC uint8 NDArray
    (reference image.py imdecode; backed by PIL instead of OpenCV)."""
    if isinstance(buf, nd.NDArray):
        buf = bytes(buf.asnumpy().astype("uint8").tobytes())
    img = _pil().open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = onp.asarray(img)
    if not to_rgb and flag:
        arr = arr[..., ::-1]  # BGR like OpenCV default
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return nd.array(onp.ascontiguousarray(arr), dtype="uint8")


def imread(filename, flag=1, to_rgb=True):
    """Reference: image.py imread."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    """Reference: image.py imresize (bilinear default)."""
    Image = _pil()
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else onp.asarray(src)
    mode_in = arr.astype("uint8")
    resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                3: Image.LANCZOS}.get(interp, Image.BILINEAR)
    img = Image.fromarray(mode_in.squeeze() if mode_in.shape[-1] == 1
                          else mode_in)
    img = img.resize((w, h), resample)
    out = onp.asarray(img)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out, dtype="uint8")


def resize_short(src, size, interp=2):
    """Resize the shorter side to `size` (reference image.py:_get_interp
    + resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Reference: image.py fixed_crop."""
    out = nd.NDArray(src._data[y0:y0 + h, x0:x0 + w])
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size,
                     interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = pyrandom.randint(0, max(w - new_w, 0))
    y0 = pyrandom.randint(0, max(h - new_h, 0))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size,
                     interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area+aspect crop (reference image.py random_size_crop /
    the Inception-style aug)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        aspect = onp.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * aspect) ** 0.5))
        new_h = int(round((target_area / aspect) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    """Reference: image.py color_normalize."""
    arr = src._data.astype("float32") if isinstance(src, nd.NDArray) \
        else onp.asarray(src, "float32")
    mean_v = mean._data if isinstance(mean, nd.NDArray) else mean
    out = arr - mean_v
    if std is not None:
        std_v = std._data if isinstance(std, nd.NDArray) else std
        out = out / std_v
    return nd.NDArray(out)


def copyMakeBorder(src, top, bot, left, right, type=0, value=0):  # noqa: A002,N802
    arr = src.asnumpy()
    out = onp.pad(arr, ((top, bot), (left, right), (0, 0)),
                  mode="constant", constant_values=value)
    return nd.array(out, dtype=str(arr.dtype))


# ------------------------------------------------------------- augmenters
class Augmenter:
    """Reference: image.py Augmenter base."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = (size, area,
                                                         ratio, interp)

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.NDArray(src._data[:, ::-1])
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd.NDArray(src._data.astype("float32") * alpha)


class ContrastJitterAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        arr = src._data.astype("float32")
        gray = (arr * self._coef).sum() * (3.0 / arr.size)
        return nd.NDArray(arr * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        arr = src._data.astype("float32")
        gray = (arr * self._coef).sum(axis=2, keepdims=True)
        return nd.NDArray(arr * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    """Reference image.py HueJitterAug (yiq rotation)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = onp.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], "float32")
        self.ityiq = onp.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], "float32")

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = onp.cos(alpha * onp.pi)
        w = onp.sin(alpha * onp.pi)
        bt = onp.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       "float32")
        t = onp.dot(onp.dot(self.ityiq, bt), self.tyiq).T
        arr = src._data.astype("float32")
        return nd.NDArray(arr @ t)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-noise lighting (reference image_aug_default.cc pca noise)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, "float32")
        self.eigvec = onp.asarray(eigvec, "float32")

    def __call__(self, src):
        alpha = onp.random.normal(0, self.alphastd, size=(3,)).astype(
            "float32")
        rgb = onp.dot(self.eigvec * alpha, self.eigval)
        return nd.NDArray(src._data.astype("float32") + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = onp.asarray(mean, "float32") if mean is not None \
            else None
        self.std = onp.asarray(std, "float32") if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = src._data.astype("float32")
            gray = (arr * self._coef).sum(axis=2, keepdims=True)
            return nd.NDArray(onp.broadcast_to(gray, arr.shape).copy())
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,  # noqa: N802
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference image.py:1025)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Pure-python image iterator over .rec files or .lst+directory
    (reference image.py ImageIter:1139)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imgrec=None, dtype="float32", last_batch_handle="pad",
                 **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.dtype = dtype
        self._shuffle = shuffle
        if last_batch_handle not in ("pad", "discard"):
            raise MXNetError(
                f"last_batch_handle={last_batch_handle!r} not supported "
                "(pad | discard)")
        self._last_batch_handle = last_batch_handle
        self._records = []  # list of (label_array|None, payload | path)
        self._mm = None
        if path_imgrec:
            # mmap + frame once: records are memoryviews into the file
            # (no up-front copy of a possibly-huge .rec); labels are
            # unpacked lazily per sample
            import mmap as _mmap

            from .. import _native

            self._rec_file = open(path_imgrec, "rb")
            self._mm = _mmap.mmap(self._rec_file.fileno(), 0,
                                  access=_mmap.ACCESS_READ)
            if _native.get_lib() is not None:
                payloads = _native.parse_records(self._mm)
            else:
                reader = recordio.MXRecordIO(path_imgrec, "r")
                payloads = []
                while True:
                    s = reader.read()
                    if s is None:
                        break
                    payloads.append(s)
                reader.close()
            self._records = [(None, p) for p in payloads]
        elif path_imglist:
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = onp.asarray([float(x) for x in parts[1:-1]],
                                        "float32")
                    self._records.append(
                        (label, os.path.join(path_root, parts[-1])))
        else:
            raise MXNetError("need path_imgrec or path_imglist")
        if num_parts > 1:  # sharding (kv.num_workers / rank)
            self._records = self._records[part_index::num_parts]
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self._order = list(range(len(self._records)))
        self.reset()

    @property
    def provide_data(self):
        from ..io.io import DataDesc

        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         self.dtype)]

    @property
    def provide_label(self):
        from ..io.io import DataDesc

        return [DataDesc("softmax_label",
                         (self.batch_size, self.label_width)
                         if self.label_width > 1
                         else (self.batch_size,), "float32")]

    def reset(self):
        if self._shuffle:
            pyrandom.shuffle(self._order)
        self._cursor = 0

    def close(self):
        """Release the mmap/file handle (pair of the lazy .rec mmap)."""
        self._records = []
        if self._mm is not None:
            self._mm.close()
            self._rec_file.close()
            self._mm = None

    def next_sample(self):
        if self._cursor >= len(self._records):
            raise StopIteration
        label, src = self._records[self._order[self._cursor]]
        self._cursor += 1
        if isinstance(src, (bytes, memoryview)):
            if label is None:  # .rec payload: unpack header lazily
                header, img_bytes = recordio.unpack(bytes(src))
                label = onp.atleast_1d(onp.asarray(header.label,
                                                   "float32"))
                img = imdecode(img_bytes)
            else:
                img = imdecode(src)
        else:
            img = imread(src)
        return label, img

    def next(self):
        c, h, w = self.data_shape
        batch = onp.zeros((self.batch_size, h, w, c), "float32")
        labels = onp.zeros((self.batch_size, self.label_width), "float32")
        i = 0
        try:
            while i < self.batch_size:
                label, img = self.next_sample()
                for aug in self.auglist:
                    img = aug(img)
                arr = img.asnumpy()
                if arr.shape[:2] != (h, w):
                    arr = imresize(nd.array(arr.astype("uint8")), w,
                                   h).asnumpy()
                batch[i] = arr.astype("float32")
                labels[i, :len(label)] = label[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0 or (i < self.batch_size
                          and self._last_batch_handle == "discard"):
                raise
        pad = self.batch_size - i
        data = nd.array(batch.transpose(0, 3, 1, 2))  # NCHW
        lab = nd.array(labels[:, 0] if self.label_width == 1 else labels)
        return DataBatch(data=[data], label=[lab], pad=pad)
