"""Device contexts mapped onto JAX devices.

Reference parity: ``python/mxnet/context.py`` (Context, cpu()/gpu(),
current_context, num_gpus) — see SURVEY.md §2.7.  TPU-native redesign:
a Context names a ``jax.Device``; there are no streams or engine worker
threads to manage (XLA's async dispatch replaces the reference's
ThreadedEnginePerDevice, src/engine/threaded_engine_perdevice.cc:79-116).

``gpu(i)`` is kept for API compatibility and resolves to the i-th
accelerator device (TPU when present), so reference scripts written
against ``mx.gpu(0)`` run unchanged on TPU.
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

__all__ = [
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "cpu_pinned",
    "current_context",
    "num_gpus",
    "num_tpus",
]


def _cpu_devices():
    # local_devices: under jax.distributed, jax.devices() spans every
    # process and remote devices are non-addressable — eager placement
    # must stay on this worker's own devices
    devs = (jax.local_devices(backend="cpu")
            if jax.default_backend() != "cpu" else jax.local_devices())
    return devs


def _accel_devices():
    """This process's non-CPU jax devices (TPU chips); empty on
    CPU-only hosts."""
    try:
        devs = jax.local_devices()
    except RuntimeError:
        return []
    return [d for d in devs if d.platform != "cpu"]


class Context:
    """A device context. devtype ids mirror the reference's Context enum
    (include/mxnet/base.h kCPU=1 kGPU=2 kCPUPinned=3) with TPU added."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            device_id = device_type.device_id
            device_type = device_type.device_type
        if device_type not in self.devstr2type:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = device_id
        self._old_ctx = None

    @property
    def device_typeid(self):
        return self.devstr2type[self.device_type]

    def jax_device(self) -> jax.Device:
        """Resolve to a concrete jax.Device (the TPU chip or a host CPU)."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = _cpu_devices()
            return devs[min(self.device_id, len(devs) - 1)]
        accel = _accel_devices()
        if not accel:  # CPU-only host: fall back so gpu(0) code still runs
            devs = _cpu_devices()
            return devs[self.device_id % len(devs)]
        if self.device_id >= len(accel):
            raise MXNetError(
                f"device {self} out of range: {len(accel)} accelerator(s)"
            )
        return accel[self.device_id]

    @property
    def _canon(self):
        """gpu and tpu name the same accelerator chips — equal for
        placement/grouping purposes."""
        return "gpu" if self.device_type == "tpu" else self.device_type

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self._canon == other._canon
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self._canon, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx

    def empty_cache(self):
        """Reference: MXStorageEmptyCache. XLA owns HBM; nothing to do."""


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Alias for the i-th accelerator (TPU chip) for reference-API parity."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value


def num_gpus():
    """Number of accelerator chips visible (reference: mx.context.num_gpus)."""
    return len(_accel_devices())


def num_tpus():
    return len(_accel_devices())
