"""Weight initializer registry.

Reference parity: python/mxnet/initializer.py (770 LoC) — ``Initializer``
base with a string registry, Uniform/Normal/Orthogonal/Xavier/MSRAPrelu/
Bilinear/LSTMBias/Constant and the ``InitDesc`` attribute protocol.
TPU-native redesign: initializers produce values with numpy on host (cheap,
one-time) and the result is device_put by the Parameter; no RNG resource
management is needed.
"""
from __future__ import annotations

import json
import math
import re

import numpy as onp

from .base import MXNetError

__all__ = [
    "InitDesc",
    "Initializer",
    "register",
    "create",
    "Zero",
    "One",
    "Constant",
    "Uniform",
    "Normal",
    "Orthogonal",
    "Xavier",
    "MSRAPrelu",
    "Bilinear",
    "LSTMBias",
    "Load",
    "Mixed",
]

_REGISTRY: dict[str, type] = {}


def register(klass):
    """Class decorator: register an Initializer under its lowercase name."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, *args, **kwargs):
    if isinstance(name, Initializer):
        return name
    if callable(name) and not isinstance(name, type):
        return _WrapFn(name)
    key = name.lower() if isinstance(name, str) else name
    if key not in _REGISTRY:
        raise MXNetError(f"unknown initializer {name!r}")
    return _REGISTRY[key](*args, **kwargs)


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (reference
    initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base: callable on (InitDesc/name, numpy out buffer shape) -> ndarray.

    Matches the reference dispatch (initializer.py __call__): names ending
    in specific suffixes get default treatments unless the desc carries an
    ``__init__`` attr override.
    """

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, shape, dtype="float32"):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init = desc.attrs.get("__init__", "")
        if init:
            return create(json.loads(init)[0], **json.loads(init)[1])._init(
                desc, shape, dtype
            )
        name = str(desc)
        if name.endswith("weight"):
            return self._init_weight_d(desc, shape, dtype)
        if name.endswith("bias"):
            return self._zeros(shape, dtype)
        if name.endswith("gamma"):
            return self._ones(shape, dtype)
        if name.endswith("beta"):
            return self._zeros(shape, dtype)
        if name.endswith("running_mean") or name.endswith("moving_mean"):
            return self._zeros(shape, dtype)
        if name.endswith("running_var") or name.endswith("moving_var"):
            return self._ones(shape, dtype)
        if name.endswith("min") or name.endswith("max"):
            return self._zeros(shape, dtype)
        return self._init_weight_d(desc, shape, dtype)

    # -- internals ------------------------------------------------------
    def _init_weight_d(self, desc, shape, dtype):
        return onp.asarray(self._init_weight(desc, shape), dtype=dtype)

    def _init(self, desc, shape, dtype):
        return onp.asarray(self._init_weight(desc, shape), dtype=dtype)

    def _init_weight(self, name, shape):
        raise NotImplementedError

    @staticmethod
    def _zeros(shape, dtype):
        return onp.zeros(shape, dtype=dtype)

    @staticmethod
    def _ones(shape, dtype):
        return onp.ones(shape, dtype=dtype)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._kwargs})"


class _WrapFn(Initializer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def _init_weight(self, name, shape):
        out = onp.zeros(shape, dtype="float32")
        r = self._fn(name, out)
        return out if r is None else r


@register
class Zero(Initializer):
    def _init_weight(self, name, shape):
        return onp.zeros(shape)


_REGISTRY["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, name, shape):
        return onp.ones(shape)


_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, shape):
        return onp.full(shape, self.value)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, shape):
        return onp.random.uniform(-self.scale, self.scale, size=shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, shape):
        return onp.random.normal(0, self.sigma, size=shape)


@register
class Orthogonal(Initializer):
    """Saxe et al. 2013 exact solutions init (reference initializer.py)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, shape):
        nout = shape[0]
        nin = int(onp.prod(shape[1:])) if len(shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = onp.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = onp.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = onp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        return (self.scale * q).reshape(shape)


@register
class Xavier(Initializer):
    """Glorot init; magnitude/factor_type semantics match the reference."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(
            rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude
        )
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, shape):
        if len(shape) < 2:
            raise MXNetError(
                f"Xavier requires >=2D shape for {name}, got {shape}"
            )
        hw_scale = float(onp.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {
            "avg": (fan_in + fan_out) / 2.0,
            "in": fan_in,
            "out": fan_out,
        }.get(self.factor_type)
        if factor is None:
            raise MXNetError("Incorrect factor type")
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            return onp.random.uniform(-scale, scale, size=shape)
        if self.rnd_type == "gaussian":
            return onp.random.normal(0, scale, size=shape)
        raise MXNetError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (deconv UpSampling weights)."""

    def _init_weight(self, name, shape):
        weight = onp.zeros(int(onp.prod(shape)), dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return weight.reshape(shape)


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, others 0 (reference semantics:
    gate order i, f, c, o in the fused RNN weight layout)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, shape):
        b = onp.zeros(shape)
        num_hidden = shape[0] // 4
        b[num_hidden : 2 * num_hidden] = self.forget_bias
        return b


class Load:
    """Init from a dict of arrays, falling back to default_init."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {
            k.replace("arg:", "").replace("aux:", ""): v
            for k, v in param.items()
        }
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, shape, dtype="float32"):
        if name in self.param:
            arr = self.param[name]
            arr = arr.asnumpy() if hasattr(arr, "asnumpy") else onp.asarray(arr)
            if tuple(arr.shape) != tuple(shape):
                raise MXNetError(
                    f"Parameter {name} cannot be initialized from loading. "
                    f"Shape mismatch, target {shape} vs loaded {arr.shape}"
                )
            return onp.asarray(arr, dtype=dtype)
        if self.default_init is None:
            raise MXNetError(
                f"Cannot Initialize parameter {name}: not found in loaded "
                "params and no default initializer"
            )
        return self.default_init(name, shape, dtype)


class Mixed:
    """Patterns -> initializers, first regex match wins (reference Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers length mismatch")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, shape, dtype="float32"):
        for prog, init in self.map:
            if prog.match(str(name)):
                return init(name, shape, dtype)
        raise MXNetError(
            f"Parameter name {name} did not match any pattern. "
            'Consider adding a ".*" pattern at the end.'
        )
