"""KVStore — the data-parallel communication layer.

Reference parity: include/mxnet/kvstore.h + python/mxnet/kvstore.py
(init/push/pull/pushpull, optimizer-on-store, rank/num_workers/barrier,
gradient compression) with backends local/device (src/kvstore/comm.h),
nccl (kvstore_nccl.h) and dist_sync/dist_async (ps-lite,
kvstore_dist.h / kvstore_dist_server.h).

TPU-native redesign (SURVEY.md §2.5): a single logical copy of every
value lives as a jax.Array; "device aggregation" of a list of per-shard
gradients is a jnp tree-sum (XLA fuses it); multi-host `dist_*` modes ride
``jax.distributed`` + global collectives over the pod mesh rather than a
parameter-server process group.  Inside pjit/shard_map training steps the
same reduction is a ``lax.psum`` — the Trainer uses KVStore only at the
API boundary, exactly like the reference.  Gradient compression maps to
2-bit quantize + error-feedback residual kept as device state.
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp

from . import ndarray as nd
from .base import MXNetError
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _key_list(key):
    single = not isinstance(key, (list, tuple))
    return ([key] if single else list(key)), single


class GradientCompression:
    """2-bit gradient compression with error-feedback residual
    (reference src/kvstore/gradient_compression.h:38-121)."""

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residual = {}

    def compress(self, key, grad_v):
        r = self._residual.get(key)
        if r is None:
            r = jnp.zeros_like(grad_v)
        acc = grad_v + r
        t = self.threshold
        q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t, 0.0))
        self._residual[key] = acc - q
        return q


class KVStore:
    """Single-process KVStore covering local/device semantics; dist modes
    report rank/size from the jax.distributed runtime when initialized."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._str_keys = False
        if kv_type.startswith("dist"):
            try:
                self._rank = jax.process_index()
                self._size = jax.process_count()
            except Exception:
                self._rank, self._size = 0, 1
        else:
            self._rank, self._size = 0, 1

    # ------------------------------------------------------------ basics
    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = value if isinstance(value, (list, tuple)) else [value]
        if len(keys) != len(vals):
            raise MXNetError("key/value length mismatch")
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError(f"key {k} already initialized")
            self._store[k] = v.copy() if isinstance(v, nd.NDArray) else (
                nd.array(v))

    def push(self, key, value, priority=0):
        keys, single = _key_list(key)
        if single:
            grouped = [value if isinstance(value, list) else [value]]
        else:
            grouped = [v if isinstance(v, list) else [v] for v in value]
        for k, vlist in zip(keys, grouped):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            # device-style aggregation: tree-sum of per-device grads
            agg = vlist[0]._data
            for v in vlist[1:]:
                agg = agg + v._data
            if self._compression is not None:
                agg = self._compression.compress(k, agg)
            agg_nd = nd.NDArray(agg)
            if self._updater is not None:
                self._updater(self._key_index(k), agg_nd, self._store[k])
            else:
                # no updater: stored value becomes the pushed aggregate
                # (reference KVStore default-merge semantics)
                self._store[k]._adopt(agg.astype(self._store[k]._data.dtype))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, single = _key_list(key)
        if single:
            outs = [out if isinstance(out, list) else [out]]
        else:
            outs = [o if isinstance(o, list) else [o] for o in out]
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._store[k]
            for o in olist:
                o._adopt(src._data.astype(o._data.dtype))

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        # dense emulation (TPU-hostile sparse path; SURVEY.md §7 hard parts)
        self.pull(key, out, priority)

    def set_gradient_compression(self, compression_params):
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError(f"unsupported compression {ctype}")
        self._compression = GradientCompression(
            compression_params.get("threshold", 0.5))

    # --------------------------------------------------------- optimizer
    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _key_index(self, k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    def barrier(self):
        if self._size > 1:
            # a tiny global psum is the TPU-native barrier
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("updater is not initialized")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("updater is not initialized")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _set_updater(self, updater):
        self._updater = updater

    def _send_command_to_servers(self, head, body):
        pass  # no server processes in the TPU design


def create(name="local"):
    """Factory (reference src/kvstore/kvstore.cc:40-70)."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    valid = ("local", "device", "local_allreduce_cpu",
             "local_allreduce_device", "nccl", "dist_sync", "dist_async",
             "dist_sync_device", "dist_device_sync", "dist")
    if name not in valid:
        raise MXNetError(f"unknown KVStore type {name}")
    return KVStore(name)
