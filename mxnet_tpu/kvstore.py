"""KVStore — the data-parallel communication layer.

Reference parity: include/mxnet/kvstore.h + python/mxnet/kvstore.py
(init/push/pull/pushpull, optimizer-on-store, rank/num_workers/barrier,
gradient compression) with backends local/device (src/kvstore/comm.h),
nccl (kvstore_nccl.h) and dist_sync/dist_async (ps-lite,
kvstore_dist.h / kvstore_dist_server.h).

TPU-native redesign (SURVEY.md §2.5): a single logical copy of every
value lives as a jax.Array; "device aggregation" of a list of per-shard
gradients is a jnp tree-sum (XLA fuses it); multi-host `dist_*` modes ride
``jax.distributed`` + global collectives over the pod mesh rather than a
parameter-server process group.  Inside pjit/shard_map training steps the
same reduction is a ``lax.psum`` — the Trainer uses KVStore only at the
API boundary, exactly like the reference.  Gradient compression maps to
2-bit quantize + error-feedback residual kept as device state.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as onp

from . import ndarray as nd
from .base import MXNetError
from . import optimizer as opt

__all__ = ["KVStore", "DistKVStore", "create", "init_distributed",
           "quantize_2bit", "GradientCompression"]

_dist_initialized = False


def init_distributed(coordinator=None, num_workers=None, rank=None):
    """Connect this process to the multi-host runtime.

    Reads the reference's ps-lite bootstrap env vars
    (DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT/DMLC_NUM_WORKER/DMLC_WORKER_ID,
    docs distributed_training.md:262-276) and wires them into
    ``jax.distributed.initialize`` — the TPU-native replacement for the
    ps-lite scheduler handshake.  Safe to call twice.  Launch workers
    with ``tools/launch.py`` (reference tools/launch.py:29).
    """
    global _dist_initialized
    if _dist_initialized:
        return
    from jax._src import distributed as _jd

    if _jd.global_state.coordinator_address is not None or \
            _jd.global_state.client is not None:
        # user already called jax.distributed.initialize themselves
        _dist_initialized = True
        return
    if num_workers is None:
        num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if num_workers <= 1:
        # 1-worker no-op: do NOT latch, so a later explicit call with a
        # real coordinator still takes effect
        return
    if coordinator is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
        coordinator = f"{uri}:{port}"
    if rank is None:
        # DMLC_WORKER_ID wins; under `launch.py --launcher mpi` the
        # rank comes from the MPI runtime's own env instead
        rank = int(os.environ.get(
            "DMLC_WORKER_ID",
            os.environ.get("OMPI_COMM_WORLD_RANK",
                           os.environ.get("PMI_RANK", "0"))))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_workers,
                               process_id=rank)
    _dist_initialized = True


def _key_list(key):
    single = not isinstance(key, (list, tuple))
    return ([key] if single else list(key)), single


def quantize_2bit(acc, threshold):
    """The 2-bit quantization rule as ONE pure traced function
    (reference gradient_compression-inl.h quantize_2bit kernel):
    ``acc`` (gradient + carried residual) maps to {-t, 0, +t} and the
    new residual is what quantization dropped.  Shared by the eager
    :class:`GradientCompression` below and the sharded-server step's
    per-bucket compression (parallel/__init__.py), so wire semantics
    cannot drift between the two surfaces.  Accumulation stays in
    ``acc``'s dtype — callers feed fp32 (the narrow-accumulate
    discipline for fp16/bf16 gradients)."""
    t = jnp.asarray(threshold, acc.dtype)
    q = jnp.where(acc >= t, t,
                  jnp.where(acc <= -t, -t, jnp.zeros((), acc.dtype)))
    return q, acc - q


class GradientCompression:
    """2-bit gradient compression with error-feedback residual
    (reference src/kvstore/gradient_compression.h:38-121).

    Wire format: each value quantizes to a 2-bit code (0 -> 0, 1 -> +t,
    2 -> -t), four codes per byte — a 16x payload reduction vs fp32,
    matching the reference's packed representation.  The residual
    (what quantization dropped) stays on this worker as device state
    and is added into the next round's gradient.
    """

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residual = {}

    def _quantize(self, key, grad_v, shard=None):
        """Residuals are keyed per (key, shard): a big array sliced
        into bucket-shards (MXNET_KVSTORE_BIGARRAY_BOUND) quantizes
        each slice as its own wire unit, and a shared residual would
        cross-feed one shard's error into another's next round —
        silently corrupting the error-feedback contract."""
        rk = key if shard is None else (key, shard)
        r = self._residual.get(rk)
        if r is None:
            r = jnp.zeros_like(grad_v)
        q, resid = quantize_2bit(grad_v + r, self.threshold)
        self._residual[rk] = resid
        return q

    def compress(self, key, grad_v, shard=None):
        """Local quantize-dequantize (single-process stores: no wire)."""
        return self._quantize(key, grad_v, shard=shard)

    def compress_packed(self, key, grad_v, shard=None):
        """Quantize and pack to the 2-bit wire payload (uint8)."""
        q = self._quantize(key, grad_v, shard=shard)
        codes = jnp.where(q > 0, jnp.uint8(1),
                          jnp.where(q < 0, jnp.uint8(2), jnp.uint8(0)))
        flat = codes.reshape(-1)
        pad = (-flat.size) % 4
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), jnp.uint8)])
        flat = flat.reshape(-1, 4)
        payload = (flat[:, 0] | (flat[:, 1] << 2) | (flat[:, 2] << 4)
                   | (flat[:, 3] << 6)).astype(jnp.uint8)
        return payload

    def _codes_to_values(self, codes, dtype):
        t = self.threshold
        return jnp.where(codes == 1, jnp.asarray(t, dtype),
                         jnp.where(codes == 2, jnp.asarray(-t, dtype),
                                   jnp.asarray(0.0, dtype)))

    @staticmethod
    def _unpack(p):
        p = p.astype(jnp.uint8)
        return jnp.stack(
            [p & 3, (p >> 2) & 3, (p >> 4) & 3, (p >> 6) & 3],
            axis=-1)

    def decompress(self, payload, shape, dtype=jnp.float32):
        """Unpack a 2-bit payload back to {-t, 0, +t} floats."""
        codes = self._unpack(payload).reshape(-1)
        n = 1
        for d in shape:
            n *= d
        return self._codes_to_values(codes[:n].reshape(shape), dtype)

class KVStore:
    """Single-process KVStore covering local/device semantics; dist modes
    report rank/size from the jax.distributed runtime when initialized."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._str_keys = False
        if kv_type.startswith("dist"):
            try:
                self._rank = jax.process_index()
                self._size = jax.process_count()
            except Exception:
                self._rank, self._size = 0, 1
        else:
            self._rank, self._size = 0, 1

    # ------------------------------------------------------------ basics
    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = value if isinstance(value, (list, tuple)) else [value]
        if len(keys) != len(vals):
            raise MXNetError("key/value length mismatch")
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError(f"key {k} already initialized")
            self._store[k] = v.copy() if isinstance(v, nd.NDArray) else (
                nd.array(v))

    def push(self, key, value, priority=0):
        keys, single = _key_list(key)
        if single:
            grouped = [value if isinstance(value, list) else [value]]
        else:
            grouped = [v if isinstance(v, list) else [v] for v in value]
        for k, vlist in zip(keys, grouped):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            # device-style aggregation: tree-sum of per-device grads
            agg = vlist[0]._data
            for v in vlist[1:]:
                agg = agg + v._data
            agg = self._reduce(k, agg)
            agg_nd = nd.NDArray(agg)
            if self._updater is not None:
                self._updater(self._key_index(k), agg_nd, self._store[k])
            else:
                # no updater: stored value becomes the pushed aggregate
                # (reference KVStore default-merge semantics)
                self._store[k]._adopt(agg.astype(self._store[k]._data.dtype))

    def _reduce(self, key, agg):
        """Cross-worker reduction hook; for single-process stores this
        is just the local compression round-trip (no wire exists)."""
        if self._compression is not None:
            agg = self._compression.compress(key, agg)
        return agg

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, single = _key_list(key)
        if single:
            outs = [out if isinstance(out, list) else [out]]
        else:
            outs = [o if isinstance(o, list) else [o] for o in out]
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._store[k]
            for o in olist:
                o._adopt(src._data.astype(o._data.dtype))

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference kvstore_dist.h:344):
        the result has the selected rows of the stored value and zeros
        elsewhere.  Storage stays dense-backed (TPU-hostile sparse
        compute; SURVEY.md §7 hard parts) but the row_ids semantics are
        honored, so embedding-style sparse training gets the right
        values."""
        if row_ids is None:
            return self.pull(key, out, priority)
        keys, single = _key_list(key)
        if single:
            outs = [out if isinstance(out, list) else [out]]
            rows = [row_ids if isinstance(row_ids, list) else [row_ids]]
        else:
            outs = [o if isinstance(o, list) else [o] for o in out]
            rows = [r if isinstance(r, list) else [r] for r in row_ids]
        for k, olist, rlist in zip(keys, outs, rows):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._store[k]._data
            for o, rids in zip(olist, rlist):
                idx = jnp.asarray(rids._data
                                  if isinstance(rids, nd.NDArray)
                                  else rids).astype(jnp.int32).reshape(-1)
                sel = jnp.zeros_like(src).at[idx].set(src[idx])
                o._adopt(sel.astype(o._data.dtype))

    def set_gradient_compression(self, compression_params):
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError(f"unsupported compression {ctype}")
        self._compression = GradientCompression(
            compression_params.get("threshold", 0.5))
        # the fresh compressor carries no residual state, so the
        # per-key slice-step pins (fixed by the OLD residuals' layout)
        # protect nothing anymore — let new pushes re-pin at the
        # current MXNET_KVSTORE_BIGARRAY_BOUND
        getattr(self, "_comp_slice_step", {}).clear()

    # --------------------------------------------------------- optimizer
    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _key_index(self, k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    def barrier(self):
        if self._size > 1:
            # a tiny global psum is the TPU-native barrier
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("updater is not initialized")
        # rename-atomic (resilience.checkpoint): a crash mid-write
        # leaves the previous .states intact, never a torn pickle
        from .resilience.checkpoint import atomic_write_bytes

        atomic_write_bytes(fname, self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("updater is not initialized")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _set_updater(self, updater):
        self._updater = updater

    def _send_command_to_servers(self, head, body):
        """Single-process stores have no server group; DistKVStore
        overrides this with the PS command channel."""
        raise MXNetError(
            "_send_command_to_servers needs a dist KVStore (the local "
            "store has no server processes)")


class DistKVStore(KVStore):
    """Multi-process KVStore: push/pull cross worker boundaries.

    Reference parity: KVStoreDist (src/kvstore/kvstore_dist.h:44) +
    KVStoreDistServer (kvstore_dist_server.h:155).  TPU-native: there
    are no server processes — sync-mode aggregation ("wait for all
    workers, merge, update", kvstore_dist_server.h:346-359) IS a global
    allreduce over the process group, and the "server-side optimizer"
    is the same updater run identically on every worker against the
    replicated store.  ``dist_async`` shares this bulk-synchronous
    engine (the stale-update PS semantics have no XLA analog; the
    reference treats async as a throughput knob, not a contract).
    """

    _ps_counter = 0

    def __init__(self, kv_type="dist_sync"):
        init_distributed()
        super().__init__(kv_type)
        self._rank = jax.process_index()
        self._size = jax.process_count()
        self._mesh = None
        self._sum_fn = None
        self._ps = None
        # PS key namespace: deterministic per-process creation order
        # (all ranks run the same program), isolates instances sharing
        # the process-wide PS backend
        self._ps_ns = f"s{DistKVStore._ps_counter}"
        DistKVStore._ps_counter += 1
        # keys initialized with row_sparse values: their push/pull rides
        # the PS shards with O(nnz) wire frames (kvstore_dist.h
        # PushRowSparse / PullRowSparseImpl) in EVERY dist mode
        self._sparse_keys = set()
        # wire accounting for the last push (tools/bandwidth.py and the
        # compression tests read these)
        self.last_wire_bytes = 0
        self.last_uncompressed_bytes = 0
        # per-key pinned compression slice step (see
        # _compress_packed_bigarray: residual layout must outlive
        # mid-run MXNET_KVSTORE_BIGARRAY_BOUND changes)
        self._comp_slice_step = {}

    # ------------------------------------------------ sharded PS backend
    def _ps_active(self):
        """The TCP parameter-server shards (mxnet_tpu._ps) carry:
          * dist_async — per-worker immediate apply, no peer waits
            (kvstore_dist_server.h:346-359), and
          * compressed dist_sync — the packed payload goes only to the
            key's owner shard (EncodeDefaultKey sharding,
            kvstore_dist.h:606), O(N) wire bytes per worker instead of
            the O(W*N) allgather this had in round 3.
        Uncompressed dist_sync stays on the XLA allreduce."""
        if self._size <= 1:
            return False
        return self.type == "dist_async" or self._compression is not None

    def _ps_key(self, k):
        return f"{self._ps_ns}/{k}"

    def _ps_backend(self):
        if self._ps is None:
            from ._ps import PSBackend

            self._ps = PSBackend.get(self._rank, self._size)
            if self._updater is not None:
                self._ps.set_updater(self._ps_ns, self._ps_updater())
        return self._ps

    def _ps_updater(self):
        updater = self._updater
        key_index = self._key_index

        def apply(key, grad_nd, stored_nd):
            updater(key_index(key), grad_nd, stored_nd)

        return apply

    def _push_mode(self):
        return "async" if self.type == "dist_async" else "sync"

    def _ps_op(self, k, fn):
        """Run a PS operation with shard-restart recovery: a restarted
        shard (launch.py --max-restarts) comes back EMPTY, so the first
        op against it gets 'uninitialized key' — every worker then
        refills from its own last-known value and retries.  Refills are
        deliberately FIRST-WINS set-if-absent on the server (both the
        python and native shards, _ps.py _handle/init): unlike a fresh
        ``init``, where rank-0's value is authoritative, a refill can
        arrive AFTER another worker's refill has already absorbed new
        pushes on the recovered shard, and a late rank-0 overwrite
        would silently discard those updates.  Workers' last-known
        values differ by at most the lost in-flight round, so whichever
        refill lands first is an equally valid restart point.  The
        round counters on the fresh shard start at zero, so sync pulls
        resume consistently; the round in flight at the crash is lost —
        the same loss the reference takes without a server
        checkpoint."""
        try:
            return fn()
        except MXNetError as e:
            if "uninitialized key" not in str(e):
                raise
            self._ps_backend().init(self._ps_key(k),
                                    self._store[k].asnumpy(),
                                    refill=True)
            return fn()

    def _send_command_to_servers(self, head, body):
        """Worker->server command channel over the PS protocol
        (reference KVStore::SendCommandToServers,
        kvstore_dist_server.h CommandHandle): broadcast to every
        shard.  head==0 carries the server-profiler protocol
        ('profile:start' / 'profile:stop' / 'profile:dump:<path>' —
        the KVStoreServerProfilerCommand analog,
        include/mxnet/kvstore.h:49)."""
        self._ps_backend().command(head, body)

    def num_dead_node(self, node_id=0, timeout_sec=60.0):
        """Workers whose liveness heartbeat is older than
        ``timeout_sec`` (reference get_num_dead_node,
        include/mxnet/kvstore.h:380).  Requires the PS backend (it is
        started on demand); in a 1-worker group nothing can be dead."""
        if self._size <= 1:
            return 0
        return self._ps_backend().num_dead_node(timeout_sec)

    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = value if isinstance(value, (list, tuple)) else [value]
        if len(keys) != len(vals):
            raise MXNetError("key/value length mismatch")
        if self._ps_active():
            ps = self._ps_backend()
            for k, v in zip(keys, vals):
                if k in self._store:
                    raise MXNetError(f"key {k} already initialized")
                arr = v if isinstance(v, nd.NDArray) else nd.array(v)
                if getattr(arr, "stype", "default") == "row_sparse":
                    self._sparse_keys.add(k)
                self._store[k] = arr.copy()  # dtype/shape record
                ps.init(self._ps_key(k), arr.asnumpy())
            self.barrier()  # rank-0's value is authoritative on owners
            return
        # PS inactive: sparse keys still live on the PS shards (their
        # O(nnz) wire needs server support); dense keys keep the
        # allreduce path WITH its rank-0 broadcast
        sparse_pairs = [(k, v) for k, v in zip(keys, vals)
                        if getattr(v, "stype", "default")
                        == "row_sparse"]
        dense_pairs = [(k, v) for k, v in zip(keys, vals)
                       if getattr(v, "stype", "default")
                       != "row_sparse"]
        if sparse_pairs:
            ps = self._ps_backend()
            for k, v in sparse_pairs:
                if k in self._store:
                    raise MXNetError(f"key {k} already initialized")
                self._sparse_keys.add(k)
                self._store[k] = v.copy()
                ps.init(self._ps_key(k), v.asnumpy())
            self.barrier()
        if dense_pairs:
            super(DistKVStore, self).init(
                [k for k, _ in dense_pairs],
                [v for _, v in dense_pairs])
            for k, _ in dense_pairs:
                # rank-0's value everywhere (the server owning initial
                # weights, kvstore_dist_server.h init semantics)
                self._store[k]._adopt(
                    self._broadcast0(self._store[k]._data))

    def _compress_packed_bigarray(self, k, a32):
        """Compress one push payload, slicing arrays above the live
        ``MXNET_KVSTORE_BIGARRAY_BOUND`` into bound-sized bucket-shards
        first — the ps-lite big-array slicing (kvstore_dist.h
        EncodeDefaultKey) applied to the compressed wire.  Each slice
        quantizes as its own unit with its OWN error-feedback residual
        (keyed per (key, shard) in GradientCompression — a shared
        residual would cross-feed one slice's dropped error into
        another's next round).  Slice edges are 4-aligned, so the
        concatenated payload is byte-identical to whole-array packing
        and the server-side decompress needs no changes.

        The slice step is PINNED per key at its first compressed push:
        residual shapes/offsets are fixed by the original slicing, so
        a mid-run MXNET_KVSTORE_BIGARRAY_BOUND change (the knob is
        live) applies to keys first pushed after it, never to a key
        whose residual state already exists under the old layout."""
        from .config import get_env

        flat = a32.reshape(-1)
        step = self._comp_slice_step.get(k)
        if step is None:
            bound = int(get_env("MXNET_KVSTORE_BIGARRAY_BOUND"))
            step = max(4, (bound // 4) * 4)
            self._comp_slice_step[k] = step
        if flat.size <= step:
            return onp.asarray(self._compression.compress_packed(k, a32))
        return onp.concatenate([
            onp.asarray(self._compression.compress_packed(
                k, flat[o:o + step], shard=i))
            for i, o in enumerate(range(0, flat.size, step))])

    def _push_sparse(self, k, vlist):
        """Row-sparse push: aggregate the per-device grads, ship only
        (rows, vals) to the key's owner shard — O(nnz) wire bytes."""
        agg = vlist[0]
        if len(vlist) > 1:
            from .ndarray import sparse as _sp

            dense = vlist[0]._data
            for v in vlist[1:]:
                dense = dense + v._data
            agg = _sp.RowSparseNDArray(dense)
        rows, vals = agg._compact()
        rows_np = onp.asarray(rows, onp.int64)
        vals_np = onp.asarray(vals)  # native dtype on the wire
        self.last_wire_bytes = int(rows_np.nbytes + vals_np.nbytes)
        self.last_uncompressed_bytes = int(agg._data.nbytes)
        self._ps_op(k, lambda: self._ps_backend().spush(
            self._ps_key(k), rows_np, vals_np, self._push_mode()))

    def push(self, key, value, priority=0):
        keys, single = _key_list(key)
        if any(k in self._sparse_keys for k in keys):
            if single:
                grouped = [value if isinstance(value, list) else [value]]
            else:
                grouped = [v if isinstance(v, list) else [v]
                           for v in value]
            for k, vlist in zip(keys, grouped):
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialized")
                if k in self._sparse_keys:
                    self._push_sparse(k, vlist)
                else:
                    # mixed list: dense keys take their normal route
                    DistKVStore.push(self, k, vlist, priority)
            return
        if not self._ps_active():
            return super(DistKVStore, self).push(key, value, priority)
        keys, single = _key_list(key)
        if single:
            grouped = [value if isinstance(value, list) else [value]]
        else:
            grouped = [v if isinstance(v, list) else [v] for v in value]
        ps = self._ps_backend()
        mode = self._push_mode()
        for k, vlist in zip(keys, grouped):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            agg = vlist[0]._data
            for v in vlist[1:]:
                agg = agg + v._data
            if self._compression is not None:
                # quantization math is f32; the packed wire stays 2-bit
                a32 = agg.astype(jnp.float32)
                payload = self._compress_packed_bigarray(k, a32)
                self.last_wire_bytes = int(payload.nbytes)
                self.last_uncompressed_bytes = int(agg.nbytes)
                self._ps_op(k, lambda: ps.push(
                    self._ps_key(k), None, mode,
                    compressed_payload=payload,
                    meta={"shape": tuple(a32.shape),
                          "threshold": self._compression.threshold}))
            else:
                # NATIVE dtype on the wire (the servers store and merge
                # per-dtype; the old unconditional f32 cast degraded
                # f64 and doubled half-precision wire bytes)
                wire = onp.asarray(agg)
                self.last_wire_bytes = int(wire.nbytes)
                self.last_uncompressed_bytes = int(agg.nbytes)
                self._ps_op(k, lambda: ps.push(self._ps_key(k), wire,
                                               mode))

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """O(len(row_ids)) wire: only the requested rows come back from
        the owner shard (kvstore_dist.h:344 PullRowSparseImpl); the out
        array holds those rows densely with zeros elsewhere."""
        keys, single = _key_list(key)
        if not any(k in self._sparse_keys for k in keys):
            return super(DistKVStore, self).row_sparse_pull(
                key, out, priority, row_ids)
        if row_ids is None:
            raise MXNetError("row_sparse_pull needs row_ids")
        if single:
            outs = [out if isinstance(out, list) else [out]]
            rows = [row_ids if isinstance(row_ids, list) else [row_ids]]
        else:
            outs = [o if isinstance(o, list) else [o] for o in out]
            rows = [r if isinstance(r, list) else [r] for r in row_ids]
        ps = self._ps_backend()
        for k, olist, rlist in zip(keys, outs, rows):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            if k not in self._sparse_keys:
                # mixed list: dense keys keep the base row-slice path
                super(DistKVStore, self).row_sparse_pull(
                    k, olist, priority, rlist)
                continue
            for o, rids in zip(olist, rlist):
                idx = onp.asarray(
                    rids.asnumpy() if isinstance(rids, nd.NDArray)
                    else rids, onp.int64).reshape(-1)
                vals = self._ps_op(
                    k, lambda: ps.spull(self._ps_key(k), idx))
                self.last_wire_bytes = int(idx.nbytes + vals.nbytes)
                self.last_uncompressed_bytes = int(
                    self._store[k]._data.nbytes)
                store = self._store[k]
                jidx = jnp.asarray(idx)
                jvals = jnp.asarray(vals)
                dense = jnp.zeros(store.shape, store._data.dtype)
                dense = dense.at[jidx].set(jvals.astype(dense.dtype))
                # merge the authoritative pulled rows into the local
                # mirror (dense-path parity): a later refill must not
                # re-seed the shard with this key's init-time rows
                store._adopt(store._data.at[jidx].set(
                    jvals.astype(store._data.dtype)))
                o._adopt(dense.astype(o._data.dtype))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, single = _key_list(key)
        if any(k in self._sparse_keys for k in keys) \
                and not self._ps_active():
            # sparse keys live on the PS shards even in plain dist_sync
            outs = [out if isinstance(out, list) else [out]] if single \
                else [o if isinstance(o, list) else [o] for o in out]
            ps = self._ps_backend()
            for k, olist in zip(keys, outs):
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialized")
                if k in self._sparse_keys:
                    val = jnp.asarray(self._ps_op(
                        k, lambda: ps.pull(self._ps_key(k)))).reshape(
                        self._store[k].shape)
                    # refresh the local mirror too (dense-path parity):
                    # without it a post-restart refill re-seeds the
                    # shard from init-time values, silently discarding
                    # the training the pull just fetched
                    self._store[k]._adopt(
                        val.astype(self._store[k]._data.dtype))
                    for o in olist:
                        o._adopt(val.astype(o._data.dtype))
                else:
                    DistKVStore.pull(self, k, olist, priority,
                                     ignore_sparse)
            return
        if not self._ps_active():
            return super(DistKVStore, self).pull(key, out, priority,
                                                 ignore_sparse)
        keys, single = _key_list(key)
        if single:
            outs = [out if isinstance(out, list) else [out]]
        else:
            outs = [o if isinstance(o, list) else [o] for o in out]
        ps = self._ps_backend()
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            val = jnp.asarray(
                self._ps_op(k, lambda: ps.pull(self._ps_key(k))))
            # the native shard returns flat values; restore the shape
            val = val.reshape(self._store[k].shape)
            self._store[k]._adopt(
                val.astype(self._store[k]._data.dtype))
            for o in olist:
                o._adopt(val.astype(o._data.dtype))

    def _set_updater(self, updater):
        super(DistKVStore, self)._set_updater(updater)
        if self._ps is not None and updater is not None:
            self._ps.set_updater(self._ps_ns, self._ps_updater())

    def set_optimizer(self, optimizer):
        super(DistKVStore, self).set_optimizer(optimizer)
        if self._ps_active():
            # install on this process's server shard — every worker runs
            # the same program, so every shard gets the same rule (the
            # reference ships the optimizer to servers the same way,
            # _send_command_to_servers)
            self._ps_backend().set_updater(self._ps_ns,
                                           self._ps_updater())

    @staticmethod
    def _widen(arr):
        # half-precision widens for the wire reduction; f32/f64/integer
        # dtypes travel as-is (an f32 round-trip would corrupt them)
        if arr.dtype in (jnp.float16, jnp.bfloat16):
            return arr.astype(jnp.float32), arr.dtype
        return arr, None

    def _worker_mesh(self):
        """One-device-per-process mesh: collectives ride the process
        group links (the TPU-native replacement for ps-lite ZPush —
        XLA emits a real reduce, O(N) bytes per link, not the
        O(N*size) allgather+host-sum this had before round 3)."""
        if self._mesh is None:
            import numpy as onp
            from jax.sharding import Mesh

            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
            devs = [per_proc[i] for i in sorted(per_proc)]
            self._mesh = Mesh(onp.array(devs), ("w",))
        return self._mesh

    def _allreduce(self, arr):
        if self._size == 1:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P

        a, narrow = self._widen(arr)
        mesh = self._worker_mesh()
        sharding = NamedSharding(mesh, P("w"))
        local_dev = [d for d in mesh.devices.flat
                     if d.process_index == self._rank][0]
        local = jax.device_put(a[None], local_dev)
        garr = jax.make_array_from_single_device_arrays(
            (self._size,) + tuple(a.shape), sharding, [local])
        if self._sum_fn is None:
            self._sum_fn = jax.jit(
                lambda x: x.sum(axis=0),
                out_shardings=NamedSharding(mesh, P()))
        out = self._sum_fn(garr).addressable_data(0)
        return out.astype(narrow) if narrow is not None else out

    def _broadcast0(self, arr):
        """Rank-0's value everywhere (init consistency, like the server
        owning the initial weights)."""
        if self._size == 1:
            return arr
        from jax.experimental import multihost_utils

        a, narrow = self._widen(arr)
        out = multihost_utils.broadcast_one_to_all(a)
        return out.astype(narrow) if narrow is not None else out

    def _reduce(self, key, agg):
        # NETWORK boundary (was ZPush/ZPull).  With compression at
        # size>1 this path is unreachable: `_ps_active()` routes
        # compressed pushes to the key-owner PS shard (O(N) wire per
        # worker), so the ONLY compressed path here is the size==1
        # local quantization round-trip (lossy semantics preserved so a
        # 1-worker "dist" launch trains the same model it would in a
        # group).  The round-3 allgather+host-sum branch was deleted —
        # one compressed code path lives in push()/_ps.py.
        if self._compression is not None:
            assert self._size == 1, (
                "compressed dist push must go through the PS shard "
                "(_ps_active); _reduce is the 1-worker degradation only")
            narrow = agg.dtype if agg.dtype in (jnp.float16,
                                                jnp.bfloat16) else None
            a32 = agg.astype(jnp.float32) if narrow is not None else agg
            payload = self._compress_packed_bigarray(key, a32)
            self.last_wire_bytes = int(payload.nbytes)
            self.last_uncompressed_bytes = int(agg.nbytes)
            out = self._compression.decompress(payload, a32.shape,
                                               a32.dtype)
            return out.astype(narrow) if narrow is not None else out
        self.last_wire_bytes = int(agg.nbytes)
        self.last_uncompressed_bytes = int(agg.nbytes)
        return self._allreduce(agg)


def create(name="local"):
    """Factory (reference src/kvstore/kvstore.cc:40-70).

    ``dist_*`` returns a DistKVStore; outside a launched job
    (DMLC_NUM_WORKER absent/1 and jax.distributed uninitialized) it
    degrades to a single-worker group — rank 0 of 1 — which is the
    reference behavior for a 1-worker launch, not a silent fallback to
    ``local`` semantics.
    """
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    valid = ("local", "device", "local_allreduce_cpu",
             "local_allreduce_device", "nccl", "dist_sync", "dist_async",
             "dist_sync_device", "dist_device_sync", "dist")
    if name not in valid:
        raise MXNetError(f"unknown KVStore type {name}")
    if name.startswith("dist"):
        return DistKVStore(name)
    return KVStore(name)
