"""In-step variant autotuner — the cuDNN algo-registry analog, on TPU.

Reference parity: ``cudnn_tune='fastest'`` (src/operator/nn/cudnn/
cudnn_convolution-inl.h) benchmarks candidate convolution algorithms at
Bind time and ``cudnn_algoreg-inl.h`` caches the winner per
(shape, dtype) so later binds skip the timing.  On TPU the "algorithm"
space is which lowering a registered op uses: channel-last 1x1 convs as
``dot_general`` vs the conv emitter (ops/conv.py), the Pallas fused
BN+ReLU+conv backward vs stock XLA (ops/pallas_conv.py), the
predictor's micro-batch chunking (parallel/predict.py).

The r05 lesson drives the design: the Pallas kernel WON in isolation
(0.48 vs 1.18 ms) and LOST in-step (54.8 vs 46.3 ms) because XLA's
layout assignment and fusion decisions around the variant change with
it.  So variants are timed **inside a jitted representative step** —
the caller's real train/predict program, chained through a
``lax.fori_loop`` carry so iterations serialize and ONE readback
closes the pipeline (host-loop timing is unreliable on the tunnel,
bench.py MEASUREMENT NOTE) — never as isolated kernels.

Winners persist on disk (``autotune.json`` next to the XLA compilation
cache) keyed on (op, shape, dtype, platform, mesh); a process that
sees the same key again — or a different process on the same host —
loads the winner instead of re-timing, exactly like the cuDNN algo
registry persisting across Bind calls.

Decision precedence at trace time (``variant_choice``):

  1. ``force(...)``   — the tuner's own scope while timing a variant;
  2. an explicitly-set env var (``MXNET_CONV_1X1_DOT=1`` etc.) — the
     user's hand override, also what bench.py --conv-ab uses per arm;
  3. ``program_scope(...)`` — cached winners applied by the jit entry
     points (make_train_step, CachedOp, Executor) for their program's
     input signature;
  4. the op's registered default.

``MXNET_AUTOTUNE`` (config.py): 0 = off (no consult, no tune);
1 = consult cache + tune where the caller provides sample data
(default); 2 = re-tune even on a cache hit (cudnn_tune='fastest'
semantics on every bind).
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["variant_choice", "force", "program_scope", "lookup",
           "record", "tune", "tune_train_step", "mesh_desc",
           "cache_path", "cache_clear", "last_report",
           "dtype_ladder_armed", "ladder_rungs", "chain_time",
           "VARIANT_OPS", "op_variants"]

#: op -> {variant name: forced value}.  The forced value is what the
#: op's trace-time ``variant_choice`` consumer receives.
VARIANT_OPS = {
    "conv1x1_dot": {"conv": False, "dot": True},
    # round 14: three-way — "stock" (the unfused layer path, the r05
    # in-step winner), "jnp" (the fused op's jnp backward), "pallas"
    # (the fused op's one-pass kernel backward).  All three race
    # in-step so the per-shape winner is measured, not documented.
    "pallas_bnreluconv": {"stock": "stock", "jnp": "jnp",
                          "pallas": "pallas"},
    # round 14: the Pallas fused-bucket optimizer kernels
    # (ops/pallas_opt.py) vs the jnp fused_bucket_update baseline,
    # consulted by parallel.zero.bucket_shard_update
    "fused_bucket_opt": {"jnp": False, "pallas": True},
    # round 14: flash-attention lowering incl. block-size sub-variants
    # and the aligned-padding shim (ops/flash_attention.py)
    "flash_attention": {"naive": "naive", "pallas": "pallas",
                        "pallas_b256": "pallas_b256",
                        "pallas_pad": "pallas_pad"},
    # round 14: the bf16 dtype-ladder arm — make_train_step's compute
    # dtype raced fp32 vs bf16 (amp_cast_params) per program signature;
    # consulted only when the MXNET_DTYPE_LADDER knob arms it (a dtype
    # change is not numerics-neutral, so adoption is opt-in).
    # round 19 adds the fp8 rung (e4m3 fwd / e5m2 grad with delayed
    # per-tensor scaling, ops/pallas_opt.fp8_qdq) — raced only when
    # the knob's roster names it (ladder_rungs), never implied by a
    # bare MXNET_DTYPE_LADDER=1
    "dtype_ladder": {"fp32": "fp32", "bf16": "bf16", "fp8": "fp8"},
    # round 18: the int8 quantized-inference arms — a rewritten net's
    # QuantizedConv/QuantizedDense wrappers consult these at trace
    # (mxnet_tpu.quantization.rewrite): True runs the calibrated int8
    # program, False the wrapped fp32 layer.  quantization.
    # tune_quantized races them inside a chained run of the real
    # inference forward, so int8 is adopted per (op, shape, platform)
    # only where it measures a win.  round 19 adds the fp8 arm
    # (e4m3 operands, f32 accumulation, calibrated amax scales) to
    # the same per-op race.
    "quantized_conv": {"fp32": False, "int8": True, "fp8": "fp8"},
    "quantized_fc": {"fp32": False, "int8": True, "fp8": "fp8"},
    # round 17: decode-time attention over the PAGED kv cache
    # (ops/flash_attention.paged_decode_attention) — "gather"
    # materializes each slot's pages then runs one fused masked
    # softmax (XLA's fusion, wins at small pools), "paged" walks the
    # page list with an online-softmax accumulator (the vLLM-style
    # schedule, wins when the page table is long).  Raced by the
    # generative server's warmup on the real pool shapes.
    "paged_decode_attention": {"gather": "gather", "paged": "paged"},
}


def _parse_bool(raw):
    return raw.lower() in ("1", "true", "yes", "on")


def _parse_flash(raw):
    lowered = raw.lower()
    if lowered in ("0", "false", "no", "off", "naive"):
        return "naive"
    if lowered in ("1", "true", "yes", "on", "pallas"):
        return "pallas"
    if lowered in ("pallas_b256", "pallas_pad"):
        return lowered
    return None  # unknown value: no override


#: MXNET_DTYPE_LADDER rung spellings -> canonical rung name
_LADDER_TOKENS = {
    "fp32": "fp32", "float32": "fp32",
    "bf16": "bf16", "bfloat16": "bf16",
    "fp8": "fp8", "float8": "fp8", "e4m3": "fp8",
}


def _parse_ladder(raw):
    lowered = raw.lower()
    if "," in lowered:
        return None  # a roster ("fp32,bf16,fp8"): armed, race decides
    if lowered in ("bf16", "bfloat16"):
        return "bf16"
    if lowered in ("fp8", "float8", "e4m3"):
        return "fp8"
    if lowered in ("0", "off", "fp32", "float32"):
        return "fp32"
    return None  # "1"/"auto": armed, but no hand override


def ladder_rungs():
    """The dtype-ladder rungs this process may race/apply, parsed from
    MXNET_DTYPE_LADDER: a comma roster ("fp32,bf16,fp8") names them
    explicitly, a single rung pins it (and is the only rung), and the
    legacy arming values ("1"/"auto"/...) keep the round-14 pair —
    fp8 NEVER joins implicitly, because its delayed-scaling state must
    be provisioned in opt_state at build time and its numerics are a
    bigger departure than bf16's.  () when the ladder is unarmed."""
    raw = os.environ.get("MXNET_DTYPE_LADDER")
    if raw is None or not dtype_ladder_armed():
        return ()
    lowered = raw.lower()
    if "," in lowered:
        out = []
        for tok in lowered.split(","):
            rung = _LADDER_TOKENS.get(tok.strip())
            if rung is not None and rung not in out:
                out.append(rung)
        return tuple(out)
    single = _LADDER_TOKENS.get(lowered)
    if single is not None:
        return (single,)
    return ("fp32", "bf16")  # "1"/"auto": the round-14 race pair


def _parse_bnreluconv(raw):
    lowered = raw.lower()
    return lowered if lowered in ("stock", "jnp", "pallas") else None


def _parse_paged(raw):
    """MXNET_PAGED_ATTENTION: gather/0 pins the dense-gather decode
    attention, paged/1 the online-softmax page walk; anything else
    (e.g. 'auto') carries no override — the measured winner decides."""
    lowered = raw.lower()
    if lowered in ("0", "false", "no", "off", "gather", "dense"):
        return "gather"
    if lowered in ("1", "true", "yes", "on", "paged"):
        return "paged"
    return None


def _parse_quantize(raw):
    """MXNET_QUANTIZE: 0/off/fp32 pins the fp32 fallback arm,
    1/on/int8 pins the int8 program, fp8 pins the fp8 program
    (round 19); anything else (e.g. 'auto') carries no override —
    the measured winner decides."""
    lowered = raw.lower()
    if lowered in ("0", "false", "no", "off", "fp32", "float32"):
        return False
    if lowered in ("1", "true", "yes", "on", "int8"):
        return True
    if lowered in ("fp8", "float8", "e4m3"):
        return "fp8"
    return None


#: env var that explicitly overrides each variant op (precedence 2),
#: with a per-op parser from the raw env string to the forced value
#: (None = this raw value carries no override)
_ENV_OVERRIDE = {
    "conv1x1_dot": ("MXNET_CONV_1X1_DOT", _parse_bool),
    "fused_bucket_opt": ("MXNET_PALLAS_OPT", _parse_bool),
    "flash_attention": ("MXNET_FLASH_ATTENTION", _parse_flash),
    "dtype_ladder": ("MXNET_DTYPE_LADDER", _parse_ladder),
    "pallas_bnreluconv": ("MXNET_BNRELUCONV_VARIANT",
                          _parse_bnreluconv),
    # round 18: ONE knob hand-overrides both int8 arms (the operator
    # story is "quantization on/off", not per-op)
    "quantized_conv": ("MXNET_QUANTIZE", _parse_quantize),
    "quantized_fc": ("MXNET_QUANTIZE", _parse_quantize),
    "paged_decode_attention": ("MXNET_PAGED_ATTENTION", _parse_paged),
}


def dtype_ladder_armed():
    """The bf16 ladder arm races/applies only when the knob arms it:
    MXNET_DTYPE_LADDER set to anything but '0'/'off'/'fp32'-like.  A
    cached bf16 winner changes step numerics, so it never applies to a
    caller that did not opt in."""
    raw = os.environ.get("MXNET_DTYPE_LADDER")
    if raw is None:
        return False
    return raw.lower() not in ("", "0", "off", "false", "no")

_tls = threading.local()
_lock = threading.Lock()
_mem = {"path": None, "mtime": None, "entries": {}}
_last_report = {}


# ------------------------------------------------------------ decisions
def _get_scope(name):
    return getattr(_tls, name, None) or {}


class _Scope:
    def __init__(self, name, choices):
        self._name = name
        self._choices = dict(choices)

    def __enter__(self):
        self._prev = getattr(_tls, self._name, None)
        merged = dict(self._prev or {})
        merged.update(self._choices)
        setattr(_tls, self._name, merged)
        return self

    def __exit__(self, *exc):
        setattr(_tls, self._name, self._prev)


def force(**choices):
    """Tuning scope: pin variant ops to concrete values while the
    representative step traces (wins over everything)."""
    return _Scope("forced", choices)


def variant_choice(op, default=None):
    """The trace-time decision an op consults (see module docstring for
    the precedence ladder).  Returns the chosen value or ``default``."""
    forced = _get_scope("forced")
    if op in forced:
        return forced[op]
    env = _ENV_OVERRIDE.get(op)
    if env is not None:
        raw = os.environ.get(env[0])
        if raw is not None:
            parsed = env[1](raw)
            if parsed is not None:
                return parsed
    applied = _get_scope("applied")
    if op in applied:
        return applied[op]
    return default


def program_scope(shape, dtype, platform=None, mesh=None):
    """Apply every cached winner matching this program's input
    signature (entered by the jit entry points around trace/call:
    make_train_step's step, CachedOp._call_cached, Executor.forward).
    No-op when autotune is off or nothing is cached for the key."""
    if not enabled():
        return _Scope("applied", {})
    entries = _load(cache_path())  # one stat/load for all variant ops
    choices = {}
    if entries:
        for op in VARIANT_OPS:
            # op_variants narrows the ladder to the armed rungs: a
            # cached fp8 winner never applies to a program whose
            # roster (and opt_state provisioning) did not opt into it
            variants = op_variants(op)
            entry = entries.get(_key(op, shape, dtype, platform, mesh))
            winner = entry.get("winner") if entry else None
            if winner is not None and winner in variants:
                choices[op] = variants[winner]
    return _Scope("applied", choices)


# ------------------------------------------------------------ the cache
def enabled(override=None):
    lvl = autotune_level() if override is None else int(bool(override))
    return lvl >= 1


def autotune_level():
    from .config import get_env

    try:
        return int(get_env("MXNET_AUTOTUNE"))
    except Exception:
        return 1


def cache_path():
    """``autotune.json`` next to the persistent XLA compilation cache
    (the cudnn algo registry persisted beside the cubin cache)."""
    from .config import get_env

    d = get_env("MXNET_AUTOTUNE_CACHE_DIR") or \
        get_env("JAX_COMPILATION_CACHE_DIR") or \
        os.path.join(os.path.expanduser("~"), ".cache", "mxnet_tpu")
    return os.path.join(d, "autotune.json")


def _current_platform():
    try:
        from .ops import pallas_conv as _pc

        hint = getattr(_pc._hint, "platform", None)
        if hint is not None:
            return hint
    except Exception:
        pass
    try:
        import jax

        return jax.local_devices()[0].platform
    except Exception:
        return "unknown"


def mesh_desc(mesh):
    """Stable string key for a jax Mesh (or None)."""
    if mesh is None:
        return "none"
    try:
        return ",".join(f"{n}={s}" for n, s in
                        zip(mesh.axis_names, mesh.devices.shape))
    except Exception:
        return "mesh"


def _key(op, shape, dtype, platform, mesh):
    platform = platform or _current_platform()
    mesh = mesh if isinstance(mesh, str) else mesh_desc(mesh)
    return "|".join((op, str(tuple(shape)), str(dtype), platform, mesh))


def _sane_entries(data):
    """The entries dict of a parsed autotune.json, with anything a
    corrupt/partially-written file could smuggle dropped: a non-dict
    root or entries value becomes empty, non-dict entry values are
    filtered — so every consumer's entry.get() stays safe and a
    corrupt cache can only ever cost a re-measurement.  One helper
    shared by the read (_load) and read-merge-write (_save) paths so
    the sanitization rules cannot drift."""
    entries = data.get("entries", {}) if isinstance(data, dict) else {}
    if not isinstance(entries, dict):
        entries = {}
    return {k: v for k, v in entries.items() if isinstance(v, dict)}


def _load(path):
    """mtime-checked load so winners recorded by ANOTHER process on the
    same host are visible without restarting (algo-registry sharing)."""
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return {}
    with _lock:
        if _mem["path"] == path and _mem["mtime"] == mtime:
            return _mem["entries"]
    try:
        with open(path) as f:
            entries = _sane_entries(json.load(f))
    except (OSError, ValueError):
        entries = {}
    with _lock:
        _mem.update(path=path, mtime=mtime, entries=entries)
    return entries


def _save(path, new_entries):
    """Read-merge-write under an exclusive flock + atomic rename:
    concurrent tuners — other threads via _lock, other PROCESSES via
    the .lock file — lose no winners (last writer wins per key only)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with _lock:
        lock_f = open(f"{path}.lock", "a+")
        try:
            try:
                import fcntl

                fcntl.flock(lock_f, fcntl.LOCK_EX)
            except ImportError:  # non-POSIX: thread lock only
                pass
            try:
                with open(path) as f:
                    on_disk = _sane_entries(json.load(f))
            except (OSError, ValueError):
                on_disk = {}
            on_disk.update(new_entries)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump({"version": 1, "entries": on_disk}, f,
                          indent=1)
            os.replace(tmp, path)
            _mem.update(path=path, entries=on_disk,
                        mtime=os.stat(path).st_mtime_ns)
        finally:
            lock_f.close()  # releases the flock


def lookup(op, shape, dtype, platform=None, mesh=None):
    """Cached winner (variant name / JSON value) or None."""
    entry = _load(cache_path()).get(_key(op, shape, dtype, platform,
                                         mesh))
    if entry is None:
        return None
    return entry.get("winner")


def lookup_entry(op, shape, dtype, platform=None, mesh=None):
    return _load(cache_path()).get(_key(op, shape, dtype, platform,
                                        mesh))


def record(op, shape, dtype, winner, timings=None, platform=None,
           mesh=None):
    """Persist a winner (timings in seconds ride along for the report)."""
    entry = {"winner": winner, "timings": timings or {},
             "recorded": time.time()}
    _save(cache_path(), {_key(op, shape, dtype, platform, mesh): entry})
    return entry


def cache_clear():
    """Drop the in-memory mirror (tests poke the cache dir env var)."""
    with _lock:
        _mem.update(path=None, mtime=None, entries={})


def last_report():
    """The most recent tuning session's report (bench.py JSON)."""
    return dict(_last_report)


def op_variants(op):
    """The variant roster ``op`` actually races: VARIANT_OPS[op], with
    the dtype ladder narrowed to the rungs MXNET_DTYPE_LADDER names
    (a "fp32,bf16" roster must not spend a compile measuring an fp8
    arm the caller did not opt into; a cached winner outside the
    roster is ignored by the same rule and simply re-races)."""
    variants = VARIANT_OPS[op]
    if op == "dtype_ladder":
        rungs = ladder_rungs()
        narrowed = {k: v for k, v in variants.items() if k in rungs}
        if narrowed:
            return narrowed
    return variants


# ------------------------------------------------------------- the tuner
def chain_time(fn, init, iters=8):
    """Marginal sec/iteration of ``fn(carry, i) -> carry`` measured
    INSIDE one jitted program: a dynamic-bound fori_loop threads the
    carry (iterations serialize by construction), ONE readback of the
    first carry leaf drains the pipeline, and the two-K slope cancels
    the dispatch+readback constant (bench.py methodology; host timing
    loops alone are untrustworthy on the tunnel).  The ONE shared
    timer behind every variant race — _step_chain_time, the
    ShardedBucketUpdater's exchange race, bench's fused-kernels phase
    — so a methodology fix lands everywhere at once."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def multi(k, c):
        def body(i, c_):
            return fn(c_, i)

        c2 = jax.lax.fori_loop(0, k, body, c)
        return jax.tree_util.tree_leaves(c2)[0].ravel()[0]

    def run(k):
        t0 = time.perf_counter()
        _ = float(multi(jnp.int32(k), init))
        return time.perf_counter() - t0

    run(2)  # compile (the dynamic bound keeps it to ONE program)
    t1 = run(2)
    t2 = run(2 + iters)
    return max(t2 - t1, 1e-9) / iters


def _step_chain_time(step, params, opt_state, x, y, key, iters=8):
    """:func:`chain_time` over a make_train_step-shaped
    ``step(params, opt_state, x, y, key, t) -> (loss, params,
    opt_state)`` (loss rides the carry so the readback sees it)."""
    import jax.numpy as jnp

    def body(carry, i):
        _, p_, o_ = carry
        loss, p2, o2 = step(p_, o_, x, y, key,
                            (i + 1).astype(jnp.float32))
        return (loss, p2, o2)

    return chain_time(body, (jnp.float32(0.0), params, opt_state),
                      iters=iters)


def tune(op, shape, dtype, variants, measure, platform=None, mesh=None,
         level=None):
    """Generic variant race: ``measure(variant_value)`` is called under
    ``force(op=value)`` for each candidate; the fastest wins and is
    recorded.  A cache hit (level 1) returns the stored winner WITHOUT
    measuring — the reload-skips-retiming contract.

    Returns (winner_name, report) where report carries timings (sec)
    and whether the cache answered."""
    lvl = autotune_level() if level is None else level
    if lvl < 1:
        return None, {"enabled": False}
    if lvl == 1:
        entry = lookup_entry(op, shape, dtype, platform=platform,
                             mesh=mesh)
        if entry is not None and entry.get("winner") in variants:
            _telemetry_winner(op, shape, dtype, entry["winner"],
                              cached=True)
            return entry["winner"], {"cached": True,
                                     "timings": entry.get("timings", {})}
    timings = {}
    for name, value in variants.items():
        with force(**{op: value}):
            timings[name] = measure(value)
    winner = min(timings, key=timings.get)
    record(op, shape, dtype, winner, timings=timings, platform=platform,
           mesh=mesh)
    _telemetry_winner(op, shape, dtype, winner, cached=False,
                      timings=timings)
    return winner, {"cached": False, "timings": timings}


def _telemetry_winner(op, shape, dtype, winner, cached, timings=None):
    """One run-log event per tuning decision: which variant won, for
    which signature, and whether the registry answered from cache —
    the record the compile events' ``autotune_winner`` retrace cause
    cross-references."""
    try:
        from . import telemetry

        telemetry.event(
            "autotune", op=op, shape=str(tuple(shape)),
            dtype=str(dtype), winner=winner, cached=bool(cached),
            timings={k: round(float(v), 6)
                     for k, v in (timings or {}).items()})
    except Exception:
        pass  # telemetry must never kill a tuning session


def tune_train_step(step, params, opt_state, x, y, key,
                    variant_ops=("conv1x1_dot",), platform=None,
                    mesh=None, iters=8, level=None):
    """Race each listed variant op inside the REAL train step (the
    others held at their current decision), greedily one op at a time.
    Keyed on the step's batch-input signature — the program signature
    the winners later apply to via ``program_scope``.

    Called by make_train_step when the caller supplies sample data;
    cheap on a warm cache (pure lookups, zero compiles)."""
    global _last_report
    report = {}
    decided = {}  # earlier winners pinned while later ops race
    for op in variant_ops:
        variants = op_variants(op)

        def measure(_value, _decided=dict(decided)):
            with force(**_decided):
                return _step_chain_time(step, params, opt_state, x, y,
                                        key, iters=iters)

        winner, info = tune(op, x.shape, x.dtype, variants, measure,
                            platform=platform, mesh=mesh, level=level)
        if winner is not None:
            decided[op] = variants[winner]
            report[op] = {"winner": winner, **info}
    _last_report = report
    return report
