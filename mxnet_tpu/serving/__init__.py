"""Fail-safe inference serving (round 13).

The reference ships a production predict story (c_predict_api.h + the
model-server bindings); ours was a library — ``deploy.py`` exports
jax.export artifacts, ``parallel/predict.py`` tunes microbatches —
with no service in front of them.  This package is that service: an
in-process, thread-based continuous-batching model server that is
robust by construction.

* :class:`~mxnet_tpu.serving.server.ModelServer` — request queue +
  continuous batcher (microbatch size from live queue depth, re-padded
  to a small set of bucketed batch shapes so retraces are bounded),
  deadline-aware admission control with structured load shedding,
  circuit breaker with probe-driven re-warm, SIGTERM drain, readiness/
  liveness probes, crash-safe AOT warm start from ``deploy`` artifacts.
* :class:`~mxnet_tpu.serving.server.ServeRejected` — the structured
  rejection every shed/expired/tripped request receives (never a
  silent hang).

Fault points ``serve.admit`` / ``serve.batch`` / ``serve.model`` are
registered with :mod:`mxnet_tpu.resilience.faultsim` when this package
imports, so ``MXNET_FAULT_SPEC`` drills can target the serving path.
"""
from .server import (  # noqa: F401
    ModelServer,
    ServeHandle,
    ServeRejected,
    default_buckets,
)

__all__ = ["ModelServer", "ServeHandle", "ServeRejected",
           "default_buckets"]
