"""Fail-safe inference serving (round 13).

The reference ships a production predict story (c_predict_api.h + the
model-server bindings); ours was a library — ``deploy.py`` exports
jax.export artifacts, ``parallel/predict.py`` tunes microbatches —
with no service in front of them.  This package is that service: an
in-process, thread-based continuous-batching model server that is
robust by construction.

* :class:`~mxnet_tpu.serving.server.ModelServer` — request queue +
  continuous batcher (microbatch size from live queue depth, re-padded
  to a small set of bucketed batch shapes so retraces are bounded),
  deadline-aware admission control with structured load shedding,
  circuit breaker with probe-driven re-warm, SIGTERM drain, readiness/
  liveness probes, crash-safe AOT warm start from ``deploy`` artifacts.
* :class:`~mxnet_tpu.serving.server.ServeRejected` — the structured
  rejection every shed/expired/tripped request receives (never a
  silent hang).

Round 15 scales it out (:mod:`.fleet` / :mod:`.frontend`):

* :class:`~mxnet_tpu.serving.frontend.ServeFrontend` — the thin HTTP
  network front (stdlib ``ThreadingHTTPServer``, JSON bodies) mapping
  the submit/deadline/breaker core onto the wire, structured
  rejections included.
* :class:`~mxnet_tpu.serving.fleet.ModelHost` — multi-model residency
  with explicit HBM budgeting and zero-downtime model swap (load
  beside, warm-probe, cut over between batches, roll back on a failed
  probe).
* :class:`~mxnet_tpu.serving.fleet.FleetRouter` — replicated
  ModelServer processes behind least-queue-depth routing with health
  probes, structured failover inside the original deadline,
  queue-depth-EWMA autoscaling riding the round-12
  reshard-not-restart resize, and rolling fleet-wide swaps.

Round 17 adds the GENERATIVE decode path (:mod:`.generate` /
:mod:`.kvcache`) — the workload the stateless batcher cannot serve:

* :class:`~mxnet_tpu.serving.kvcache.PagedKVPool` — fixed physical
  KV-page pool under an HBM byte budget with token-budget admission
  (pages for prompt+max_new reserved up front) and an optional int8
  storage dtype (per-(token, head) scales) that multiplies concurrent
  capacity, gated by a measured output-agreement floor.
* :class:`~mxnet_tpu.serving.generate.GenerativeServer` —
  prefill/decode disaggregation with token-level continuous batching:
  bucketed prefill (compile events bounded and counted), a
  fixed-capacity decode slot tensor whose step compiles ONCE
  (admission/eviction are in-place slot updates, never retraces), and
  the same breaker/shed/drain failure story as ModelServer.

Fault points ``serve.admit`` / ``serve.batch`` / ``serve.model`` /
``serve.prefill`` / ``serve.decode`` and ``fleet.route`` /
``fleet.replica`` / ``fleet.swap`` are registered with
:mod:`mxnet_tpu.resilience.faultsim` when this package imports, so
``MXNET_FAULT_SPEC`` drills can target the serving path.
"""
from .fleet import (  # noqa: F401
    FleetRouter,
    ModelHost,
    SwapRolledBack,
    artifact_reserved_bytes,
)
from .frontend import ServeFrontend  # noqa: F401
from .generate import (  # noqa: F401
    GenerateHandle,
    GenerativeServer,
    toy_decoder_params,
)
from .kvcache import PagedKVPool  # noqa: F401
from .server import (  # noqa: F401
    ModelServer,
    ServeHandle,
    ServeRejected,
    default_buckets,
)

__all__ = ["ModelServer", "ServeHandle", "ServeRejected",
           "default_buckets", "ModelHost", "FleetRouter",
           "ServeFrontend", "SwapRolledBack",
           "artifact_reserved_bytes", "GenerativeServer",
           "GenerateHandle", "PagedKVPool", "toy_decoder_params"]
