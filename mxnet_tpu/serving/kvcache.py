"""Paged KV-cache pool for generative decode serving (round 17).

vLLM-style paging on the repo's own parts: the pool owns a FIXED set
of physical KV pages sized to fit under an HBM byte budget (the
ModelHost admission idea, applied to per-sequence decode state), and
sequences hold ``ceil(tokens / page_tokens)`` pages reserved UP FRONT
for their whole token budget (prompt + max_new) — so admission control
is by token budget, not request count, and a sequence admitted once
can never OOM the pool mid-decode.

Physical page 0 is reserved as the null page: inactive decode slots
point their page-table rows at it and the decode step's unconditional
writes land there harmlessly (the masked-attention contract in
ops.flash_attention.paged_decode_attention guarantees nobody ever
reads it).  Allocation never hands out page 0.

Storage dtype is ``float32`` or ``int8`` — int8 pages carry one fp32
scale per (token, head) (quantization.kv), cutting the per-page cost
from ``2*L*T*H*D*4`` bytes to ``2*L*T*H*(D+4)``: at head_dim 8 the
same budget holds 2.67x the pages, which is exactly the concurrency
headroom the capacity acceptance ratio measures from this accounting.

Host-side page bookkeeping is plain Python under the caller's lock
(GenerativeServer serializes all access from its scheduler thread);
the device arrays are plain jnp buffers the decode step donates and
returns, re-installed via :meth:`set_arrays`.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..quantization.kv import kv_page_bytes, kv_quantize

__all__ = ["PagedKVPool"]


class PagedKVPool:
    """Fixed pool of physical KV pages under a byte budget."""

    def __init__(self, layers, heads, head_dim, page_tokens=None,
                 budget_bytes=None, dtype=None):
        from ..config import get_env

        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.page_tokens = int(page_tokens if page_tokens is not None
                               else get_env("MXNET_KV_PAGE_TOKENS"))
        budget = int(budget_bytes if budget_bytes is not None
                     else get_env("MXNET_KV_POOL_BUDGET"))
        dtype = str(dtype if dtype is not None
                    else get_env("MXNET_KV_DTYPE"))
        if dtype in ("fp32", "float32"):
            dtype = "float32"
        elif dtype != "int8":
            raise MXNetError(
                f"unsupported KV-cache dtype {dtype!r} "
                "(float32 or int8)")
        self.dtype = dtype
        self.budget_bytes = budget
        self.page_bytes = kv_page_bytes(self.layers, self.page_tokens,
                                        self.heads, self.head_dim,
                                        dtype)
        self.num_pages = budget // self.page_bytes
        if self.num_pages < 1:
            raise MXNetError(
                f"KV pool budget {budget} B fits no {dtype} page "
                f"({self.page_bytes} B each) — raise "
                "MXNET_KV_POOL_BUDGET or shrink MXNET_KV_PAGE_TOKENS")
        # +1: physical page 0 is the reserved null page (see module doc)
        phys = self.num_pages + 1
        shape = (self.layers, phys, self.page_tokens, self.heads,
                 self.head_dim)
        store = jnp.int8 if dtype == "int8" else jnp.float32
        self.k_pages = jnp.zeros(shape, store)
        self.v_pages = jnp.zeros(shape, store)
        if dtype == "int8":
            sshape = shape[:-1]
            self.k_scale = jnp.zeros(sshape, jnp.float32)
            self.v_scale = jnp.zeros(sshape, jnp.float32)
        else:
            self.k_scale = None
            self.v_scale = None
        self._free = list(range(1, phys))
        self._seqs = {}  # seq id -> [physical page ids]

    # ------------------------------------------------------- accounting
    @property
    def pages_in_use(self):
        return sum(len(p) for p in self._seqs.values())

    @property
    def free_pages(self):
        return len(self._free)

    @property
    def capacity_tokens(self):
        return self.num_pages * self.page_tokens

    def pages_needed(self, tokens):
        return max(1, math.ceil(int(tokens) / self.page_tokens))

    def capacity_sequences(self, tokens_per_seq):
        """Concurrent sequences of the given token budget this pool
        admits — the page-pool-accounting number the int8-vs-fp32
        capacity acceptance ratio is measured from."""
        return self.num_pages // self.pages_needed(tokens_per_seq)

    def can_admit(self, tokens):
        return self.pages_needed(tokens) <= len(self._free)

    # ------------------------------------------------------- allocation
    def alloc(self, seq_id, tokens):
        """Reserve pages for a sequence's WHOLE token budget; returns
        the physical page list (logical order)."""
        if seq_id in self._seqs:
            raise MXNetError(f"sequence {seq_id!r} already holds pages")
        need = self.pages_needed(tokens)
        if need > len(self._free):
            raise MXNetError(
                f"pool exhausted: {need} pages needed, "
                f"{len(self._free)} free")
        pages = [self._free.pop() for _ in range(need)]
        self._seqs[seq_id] = pages
        return list(pages)

    def free(self, seq_id):
        """Return a sequence's pages to the free list (idempotent);
        returns the number reclaimed."""
        pages = self._seqs.pop(seq_id, None)
        if not pages:
            return 0
        self._free.extend(pages)
        return len(pages)

    def reset(self):
        """Reclaim EVERY page (breaker trip / drain): stale device
        data stays in place — masked attention never reads it."""
        n = self.pages_in_use
        for seq_id in list(self._seqs):
            self.free(seq_id)
        return n

    def page_table_row(self, seq_id, max_pages):
        """The sequence's page list as a fixed-width int32 row, tail
        padded with the null page."""
        pages = self._seqs.get(seq_id, [])
        if len(pages) > max_pages:
            raise MXNetError(
                f"sequence {seq_id!r} holds {len(pages)} pages, slot "
                f"rows are {max_pages} wide")
        row = onp.zeros(max_pages, onp.int32)
        row[:len(pages)] = pages
        return row

    # ----------------------------------------------------- device state
    def arrays(self):
        """(k_pages, v_pages, k_scale, v_scale) — scales are zero-size
        fp32 placeholders on an fp32 pool so the decode step's
        signature (and its single compile) is dtype-uniform."""
        if self.dtype == "int8":
            return self.k_pages, self.v_pages, self.k_scale, self.v_scale
        # two DISTINCT buffers: the decode step donates both slots
        return (self.k_pages, self.v_pages,
                jnp.zeros((0,), jnp.float32), jnp.zeros((0,), jnp.float32))

    def set_arrays(self, k_pages, v_pages, k_scale=None, v_scale=None):
        self.k_pages = k_pages
        self.v_pages = v_pages
        if self.dtype == "int8":
            self.k_scale = k_scale
            self.v_scale = v_scale

    def write_prompt(self, seq_id, k, v):
        """Write a prefilled prompt's K/V into the sequence's pages.

        ``k``/``v``: (layers, tokens, heads, head_dim) float arrays —
        only the VALID prompt tokens (bucket padding already sliced
        off).  Page-granular jitted writes: one fixed-shape program
        per pool config, compiled once however ragged the prompts."""
        pages = self._seqs.get(seq_id)
        if pages is None:
            raise MXNetError(f"sequence {seq_id!r} holds no pages")
        tokens = k.shape[1]
        t = self.page_tokens
        pad = (-tokens) % t
        if pad:
            widths = ((0, 0), (0, pad), (0, 0), (0, 0))
            k = jnp.pad(k, widths)
            v = jnp.pad(v, widths)
        n_pages = k.shape[1] // t
        for j in range(n_pages):
            kp = jax.lax.dynamic_slice_in_dim(k, j * t, t, axis=1)
            vp = jax.lax.dynamic_slice_in_dim(v, j * t, t, axis=1)
            if self.dtype == "int8":
                kq, ks = _quantize_page(kp)
                vq, vs = _quantize_page(vp)
                (self.k_pages, self.v_pages, self.k_scale,
                 self.v_scale) = _write_page_int8(
                    self.k_pages, self.v_pages, self.k_scale,
                    self.v_scale, kq, ks, vq, vs,
                    jnp.int32(pages[j]))
            else:
                self.k_pages, self.v_pages = _write_page(
                    self.k_pages, self.v_pages,
                    kp.astype(self.k_pages.dtype),
                    vp.astype(self.v_pages.dtype), jnp.int32(pages[j]))


@jax.jit
def _quantize_page(x):
    return kv_quantize(x)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _write_page(k_pages, v_pages, kp, vp, idx):
    return (k_pages.at[:, idx].set(kp), v_pages.at[:, idx].set(vp))


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _write_page_int8(k_pages, v_pages, k_scale, v_scale, kq, ks, vq, vs,
                     idx):
    return (k_pages.at[:, idx].set(kq), v_pages.at[:, idx].set(vq),
            k_scale.at[:, idx].set(ks), v_scale.at[:, idx].set(vs))
