"""Thin HTTP network front over the serving core (round 15).

The reference's model-server story is a *network* service (SURVEY §L5:
the MXNet model server speaks HTTP in front of the C predict API);
PR 8's :class:`~mxnet_tpu.serving.server.ModelServer` stopped at an
in-process Python API.  This module is the wire layer: a stdlib
``ThreadingHTTPServer`` (JSON bodies, no new dependencies) that maps
HTTP onto the existing submit/deadline/breaker core — every response a
request can get is either its model output or the SAME structured
:class:`~mxnet_tpu.serving.server.ServeRejected` reason the in-process
API raises, carried as a status code + JSON body.  No endpoint can
silently hang: the serving core's zero-silent-hangs contract is the
frontend's too.

Endpoints:

==========================  ===========================================
``POST /v1/predict``        ``{"inputs": [[...], ...], "deadline_ms"?,
                            "model"?}`` → ``{"outputs": [...],
                            "latency_ms"}`` or a structured rejection
                            (status from :data:`REJECT_STATUS`, body
                            ``{"error": reason, "detail"}``)
``GET /healthz``            readiness/liveness JSON; HTTP 200 when
                            ready, 503 otherwise — what the fleet
                            router's probe loop polls
``GET /metrics``            Prometheus text rows (``serve_ready`` /
                            ``serve_live`` gauges + the ``serve_*``
                            counters) — the same truth ``health()``
                            computes, scrapeable without the run log
``GET /v1/models``          multi-model residency report (reserved
                            bytes per model vs the HBM budget)
``POST /admin/load``        ``{"model", "path"}`` — admit another
                            ``.mxje`` artifact (507 on ``hbm_budget``)
``POST /admin/unload``      ``{"model"}``
``POST /admin/swap``        ``{"model"?, "path"}`` — zero-downtime
                            rolling swap of one replica's model; 409
                            when the warm probe failed and the old
                            model was kept (rolled back)
==========================  ===========================================

The ``fleet.replica`` fault point fires inside every predict request,
so an ``MXNET_FAULT_SPEC`` arming ``fleet.replica:crash@N`` in a
replica process kills it deterministically mid-burst — the
reproducible SIGKILL the fleet drills route around.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as onp

from ..base import MXNetError
from ..resilience import faultsim
from .server import ModelServer, ServeRejected

__all__ = ["ServeFrontend", "REJECT_STATUS", "http_call"]

#: ServeRejected reason -> HTTP status.  Back-pressure sheds map to
#: 429 (retryable by the client), lifecycle states to 503 (route to a
#: sibling), model faults to 500, and an HBM-budget admission refusal
#: to 507 (insufficient storage — literally).
REJECT_STATUS = {
    "queue_full": 429, "deadline": 429, "expired": 429,
    "breaker_open": 503, "draining": 503, "shutdown": 503,
    "no_replica": 503, "model_error": 500, "hbm_budget": 507,
}


def http_call(addr, port, method, path, body=None, timeout=10.0,
              headers=None):
    """One stdlib HTTP request (the router's client side): returns
    ``(status, payload)`` where payload is the parsed JSON body (or
    the raw text for non-JSON responses like ``/metrics``).
    ``headers`` merges extra request headers (the router's
    ``traceparent`` hop rides here).  Connection-level failures raise
    ``OSError``/``http.client`` errors — the caller's failover path.

    One fresh connection per call, deliberately: a hand-rolled pool
    shared across the router's failover/probe threads would have to
    get per-connection locking and dead-replica invalidation exactly
    right to beat a loopback TCP handshake — the wrong trade for a
    correctness-first fleet (revisit if the router ever fronts
    off-host replicas at high rates)."""
    import http.client

    conn = http.client.HTTPConnection(addr, int(port),
                                      timeout=float(timeout))
    try:
        data = None
        hdrs = dict(headers) if headers else {}
        if body is not None:
            data = json.dumps(body).encode()
            hdrs["Content-Type"] = "application/json"
        conn.request(method, path, body=data, headers=hdrs)
        resp = conn.getresponse()
        raw = resp.read()
        ctype = resp.getheader("Content-Type", "")
        if "json" in ctype and raw:
            return resp.status, json.loads(raw)
        return resp.status, raw.decode(errors="replace")
    finally:
        conn.close()


class _SingleModelHost:
    """Duck-type adapter so the frontend serves a bare ModelServer
    with the same handler the multi-model :class:`~.fleet.ModelHost`
    uses (admin load/swap endpoints answer 501 through it)."""

    def __init__(self, server):
        self.server = server

    def submit(self, x, deadline_ms=None, model=None):
        if model is not None and model != self.server.name:
            # the same request against a ModelHost replica is a 400 —
            # a wrong-model name must never silently serve THIS model
            raise MXNetError(
                f"unknown model {model!r} (this replica serves "
                f"{self.server.name!r})")
        return self.server.submit(x, deadline_ms=deadline_ms)

    def health(self):
        h = self.server.health()
        h["models"] = {self.server.name: {"ready": h["ready"],
                                          "live": h["live"]}}
        return h

    def metrics_text(self):
        h = self.server.health()
        st = self.server.stats
        return _metrics_text(
            h["ready"], h["live"],
            {"serve_requests": st["requests"],
             "serve_shed": st["shed"],
             "serve_batches": st["batches"],
             "serve_breaker_trips": st["breaker_trips"]},
            gauges={"serve_queue_depth": h["queue_depth"],
                    "serve_inflight": h["inflight"]})

    def residency(self):
        return {"budget_bytes": None, "used_bytes": None,
                "models": {self.server.name: {}}}


def _metrics_text(ready, live, counters, gauges=None):
    """Prometheus text rows: the readiness/liveness gauges first (the
    satellite contract: probes and scrapers read health()'s truth),
    then the counters, then further point-in-time gauges (queue
    depth, in-flight — values that go DOWN must not be typed counter
    or rate()/increase() reads every drain as a counter reset)."""
    lines = ["# TYPE mxnet_tpu_serve_ready gauge",
             f"mxnet_tpu_serve_ready {int(bool(ready))}",
             "# TYPE mxnet_tpu_serve_live gauge",
             f"mxnet_tpu_serve_live {int(bool(live))}"]
    for k, v in sorted(counters.items()):
        lines.append(f"# TYPE mxnet_tpu_{k} counter")
        lines.append(f"mxnet_tpu_{k} {int(v)}")
    for k, v in sorted((gauges or {}).items()):
        lines.append(f"# TYPE mxnet_tpu_{k} gauge")
        lines.append(f"mxnet_tpu_{k} {int(v)}")
    return "\n".join(lines) + "\n"


class ServeFrontend:
    """HTTP front over a :class:`~.fleet.ModelHost` (or a bare
    :class:`~mxnet_tpu.serving.server.ModelServer`).

    ``port=0`` binds an ephemeral port (read it back from ``.port``
    after :meth:`start` — the replica worker writes it to a port file
    for its router).  Request handling runs on
    ``ThreadingHTTPServer``'s per-request daemon threads, so a slow
    model batch never blocks the health endpoint.
    """

    def __init__(self, host, port=None, addr="127.0.0.1"):
        from ..config import get_env

        if isinstance(host, ModelServer):
            host = _SingleModelHost(host)
        self.host = host
        self.addr = str(addr)
        self._want_port = int(port if port is not None
                              else get_env("MXNET_FLEET_PORT"))
        self.port = None
        self._httpd = None
        self._thread = None

    def start(self):
        if self._httpd is not None:
            raise MXNetError("frontend already started")
        handler = _make_handler(self.host)
        self._httpd = ThreadingHTTPServer((self.addr, self._want_port),
                                          handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"mxnet_tpu-frontend-{self.port}", daemon=True)
        self._thread.start()
        return self

    def close(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


def _make_handler(host):
    class Handler(BaseHTTPRequestHandler):
        # one handler class per frontend: `host` rides the closure
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # request logging is telemetry's
            pass                       # job, not stderr's

        # ------------------------------------------------- plumbing
        def _read_json(self):
            n = int(self.headers.get("Content-Length", 0) or 0)
            raw = self.rfile.read(n) if n else b""
            if not raw:
                return {}
            try:
                doc = json.loads(raw)
            except ValueError as e:
                raise MXNetError(f"request body is not JSON: {e}") \
                    from e
            if not isinstance(doc, dict):
                raise MXNetError("request body must be a JSON object")
            return doc

        def _send(self, status, payload, ctype="application/json",
                  extra_headers=None):
            body = payload if isinstance(payload, bytes) else \
                json.dumps(payload).encode() if ctype.endswith("json") \
                else str(payload).encode()
            self.send_response(int(status))
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client gave up: its retry path handles it

        def _send_rejection(self, exc):
            self._send(REJECT_STATUS.get(exc.reason, 500),
                       {"error": exc.reason, "detail": exc.detail})

        # ------------------------------------------------------ GET
        def do_GET(self):
            try:
                if self.path == "/healthz":
                    h = host.health()
                    self._send(200 if h.get("ready") else 503, h)
                elif self.path == "/metrics":
                    self._send(200, host.metrics_text(),
                               ctype="text/plain; version=0.0.4")
                elif self.path == "/v1/models":
                    self._send(200, host.residency())
                else:
                    self._send(404, {"error": "not_found",
                                     "detail": self.path})
            except Exception as exc:  # noqa: BLE001 — wire layer:
                # an endpoint bug answers 500, it never kills the
                # listener thread pool
                self._send(500, {"error": "internal",
                                 "detail": repr(exc)})

        # ----------------------------------------------------- POST
        def do_POST(self):
            try:
                if self.path == "/v1/predict":
                    return self._predict()
                # read the body BEFORE any early answer: unread
                # Content-Length bytes would desync the next request
                # on an HTTP/1.1 keep-alive connection
                body = self._read_json()
                if self.path.startswith("/admin/") \
                        and not hasattr(host, "swap"):
                    # a bare ModelServer behind the frontend has no
                    # admin surface; an explicit capability probe —
                    # NOT a blanket AttributeError catch, which would
                    # disguise a real ModelHost bug as 501
                    return self._send(
                        501, {"error": "not_implemented",
                              "detail": "admin endpoints need a "
                                        "ModelHost"})
                if self.path == "/admin/swap":
                    return self._swap(body)
                if self.path == "/admin/load":
                    return self._load(body)
                if self.path == "/admin/unload":
                    host.unload(body["model"])
                    return self._send(200, {"unloaded": body["model"]})
                self._send(404, {"error": "not_found",
                                 "detail": self.path})
            except ServeRejected as exc:
                self._send_rejection(exc)
            except KeyError as exc:
                # a missing required field is the CLIENT's error
                self._send(400, {"error": "bad_request",
                                 "detail": f"missing field {exc}"})
            except MXNetError as exc:
                self._send(400, {"error": "bad_request",
                                 "detail": str(exc)})
            except Exception as exc:  # noqa: BLE001
                self._send(500, {"error": "internal",
                                 "detail": repr(exc)})

        def _predict(self):
            t0 = time.perf_counter()
            body = self._read_json()
            # the deterministic replica-death point: crash@N in a
            # replica's MXNET_FAULT_SPEC kills THIS process on its
            # N-th request, mid-burst, with no cleanup.  Fires AFTER
            # the body read so a raise-armed fault's 500 leaves no
            # unread bytes to desync a keep-alive connection
            faultsim.inject("fleet.replica")
            rows = body.get("inputs")
            if rows is None:
                raise MXNetError("predict body needs 'inputs'")
            x = onp.asarray(rows)
            deadline_ms = body.get("deadline_ms")
            model = body.get("model")
            # trace context: an inbound traceparent (the router's hop)
            # is adopted and echoed; with none, an ARMED replica roots
            # its own trace.  Unarmed with no header = no minting, no
            # echo — the zero-cost contract
            from ..telemetry import tracing
            inbound = tracing.from_header(
                self.headers.get(tracing.TRACEPARENT_HEADER))
            req_ctx = inbound.child() if inbound is not None else \
                (tracing.mint() if tracing.enabled() else None)
            bind = tracing.use(req_ctx) if req_ctx is not None \
                else contextlib.nullcontext()
            with bind:
                try:
                    handles = [host.submit(row,
                                           deadline_ms=deadline_ms,
                                           model=model) for row in x]
                except ServeRejected as exc:
                    # already-admitted sibling rows still reach their
                    # own terminal state server-side; the REQUEST is
                    # the unit of shed here
                    return self._send_rejection(exc)
                wait_s = (float(deadline_ms) / 1e3 + 30.0) \
                    if deadline_ms is not None else 120.0
                outs = []
                try:
                    for h in handles:
                        outs.append(
                            onp.asarray(h.result(timeout=wait_s)))
                except ServeRejected as exc:
                    return self._send_rejection(exc)
            t1 = time.perf_counter()
            if req_ctx is not None:
                tracing.emit_span("replica_request", t0, t1, req_ctx,
                                  kind="server", rows=int(len(x)),
                                  model=model or "")
            self._send(200, {
                "outputs": [o.tolist() for o in outs],
                "latency_ms": round((t1 - t0) * 1e3, 3),
                "model": model},
                extra_headers={tracing.TRACEPARENT_HEADER:
                               req_ctx.to_header()}
                if req_ctx is not None else None)

        def _swap(self, body):
            from .fleet import SwapRolledBack

            try:
                swap_ms = host.swap(body.get("model"), body["path"])
            except ServeRejected as exc:
                return self._send_rejection(exc)
            except SwapRolledBack as exc:
                # an ATTEMPTED swap failed and the old model kept
                # serving: an explicit 409, never a silent half-swap.
                # Refusals that never started (unknown model, another
                # swap in flight) stay plain MXNetError -> 400, so
                # the operator can tell "bad artifact" from "retry"
                return self._send(409, {"error": "swap_rolled_back",
                                        "detail": str(exc)})
            self._send(200, {"swapped": body.get("model"),
                             "swap_ms": round(float(swap_ms), 3)})

        def _load(self, body):
            host.load(body["model"], body["path"])
            self._send(200, host.residency())

    return Handler
