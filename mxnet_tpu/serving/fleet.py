"""Elastic serving fleet (round 15): replicated ModelServers behind a
fault-tolerant router.

The reference serves millions of users with a model-server fleet over
ps-lite (SURVEY §L5/§L7): many replicas, a front that routes around
dead ones, models upgraded under live traffic.  This module composes
the pieces earlier rounds built — PR 8's in-process ModelServer (the
submit/deadline/breaker core), PR 7's elastic runtime (topology
stamps, reshard verdicts, PreemptionDrain) and the PR 5/6 telemetry —
into that story, with the same contract training earned in round 12:
**a replica dying, a model upgrade, or traffic doubling is a
routed-around / drained / resized event — never dropped work or
downtime.**

* :class:`ModelHost` — multi-model residency on one replica with an
  explicit HBM budget: a ``.mxje`` artifact is admitted only when its
  ``describe_program()`` memory_analysis reserved bytes fit
  ``MXNET_FLEET_HBM_BUDGET_MB`` next to the residents, otherwise a
  structured ``ServeRejected(reason='hbm_budget')``.  Zero-downtime
  :meth:`ModelHost.swap`: the next CRC-framed artifact loads BESIDE
  the live one, a warm probe must return finite outputs, the router
  pointer cuts over between batches, the old server drains — a failed
  probe rolls back with the old model still serving.
* :class:`FleetRouter` — spreads requests across N replica server
  processes: least-queue-depth routing fed by per-replica health
  probes; structured failover (a replica whose breaker opens or whose
  process dies is ejected and the request retried on a sibling inside
  its ORIGINAL deadline via ``retry.retry_call(deadline_sec=)``);
  queue-depth-EWMA autoscaling that triggers the round-12
  reshard-not-restart resize (``reshard_verdict`` + ``resize`` event +
  ``reshards`` counter) — scale-up spawns a replica, scale-down
  SIGTERMs one, which drains through ``PreemptionDrain`` while the
  router has already stopped routing to it, so the fleet sheds
  nothing; :meth:`FleetRouter.rolling_swap` upgrades the fleet one
  replica at a time while the others keep serving.
* :func:`replica_main` — the replica worker process
  (``python -m mxnet_tpu.serving.fleet --artifact model=path ...``):
  ModelHost + the :mod:`.frontend` HTTP front on an ephemeral port
  (written to a port file), draining cleanly on SIGTERM (rc -15).

Fault points (registered here at import, so ``MXNET_FAULT_SPEC``
drills validate): ``fleet.route`` fires inside every routing
decision, ``fleet.replica`` inside every replica predict request (a
``crash`` is the deterministic mid-burst replica death), and
``fleet.swap`` inside every model swap (a ``crash`` is the mid-swap
death the rolling upgrade must survive).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as onp

from ..base import MXNetError
from ..resilience import faultsim
from ..resilience.retry import retry_call
from ..telemetry import tracing as _tracing
from .frontend import ServeFrontend, http_call
from .server import ModelServer, ServeRejected

__all__ = ["ModelHost", "FleetRouter", "SwapRolledBack",
           "GenerativeHostServer", "artifact_reserved_bytes",
           "replica_main"]


class SwapRolledBack(MXNetError):
    """A model swap failed AFTER it started (bad artifact, failed warm
    probe) and the previous artifact kept serving.  Distinct from the
    refusals that never touch the live model (unknown name, a swap
    already in flight), which raise plain MXNetError — an operator
    must be able to tell 'your artifact is bad' from 'retry in a
    moment'."""

faultsim.register_point(
    "fleet.route", "FleetRouter.submit, inside every routing decision")
faultsim.register_point(
    "fleet.replica", "replica frontend, inside every predict request "
                     "(crash = deterministic mid-burst replica death)")
faultsim.register_point(
    "fleet.swap", "ModelHost.swap, before the next artifact loads "
                  "(crash = mid-swap death)")


def _artifact_identity(path):
    """The v2 header's metadata (quantized / param_dtypes / signature)
    for the residency report — strictly a header+metadata read (a few
    hundred bytes), never the payload, never a deserialize: the load
    path already read and CRC-verified the artifact through
    ``from_artifact``, so a third full read here would sit on the
    load/swap critical path for nothing.  Pre-round-18 artifacts
    (no metadata segment) report None."""
    try:
        from .. import deploy

        return deploy.read_artifact_meta(path)
    except Exception:
        return None


def artifact_reserved_bytes(path):
    """Reserved device bytes of a ``.mxje`` artifact's program — the
    HBM-budget admission input.  Preferred source: the round-10
    ``describe_program()`` memory_analysis of the exported call
    (argument + output + temp bytes, recorded as a ``program_report``
    in any armed run log); backends without memory stats fall back to
    the in/out aval byte sizes.  Returns ``(reserved_bytes,
    exported)`` so admission does not read the artifact twice."""
    from .. import deploy

    exp = deploy.load_exported(path)
    reserved = 0
    try:
        import jax

        from .. import telemetry

        args = [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                for a in exp.in_avals]
        rep = telemetry.describe_program(
            jax.jit(exp.call), *args,
            program=f"serve_admit:{os.path.basename(str(path))}")
        mem = rep.get("memory") or {}
        reserved = sum(int(mem.get(k, 0)) for k in
                       ("argument_bytes", "output_bytes",
                        "temp_bytes"))
    except Exception:
        reserved = 0
    if not reserved:
        avals = tuple(exp.in_avals) + tuple(exp.out_avals)
        reserved = sum(
            int(onp.prod([int(s) for s in a.shape]) or 1)
            * onp.dtype(a.dtype).itemsize for a in avals)
    return int(reserved), exp


class GenerativeHostServer:
    """The ModelServer-shaped adapter a :class:`ModelHost` wraps
    around a *generative* ``.mxje`` artifact (round 18 — PR 17's
    fleet-swap leftover): builds a
    :class:`~mxnet_tpu.serving.generate.GenerativeServer` from the
    artifact's param payload + ``gen`` header config and exposes the
    submit / health / drain / close surface the host, the HTTP
    frontend and the rolling swap drive.

    Requests are rows of token ids (the swap's zeros warm probe is a
    legal all-``<token 0>`` prompt of the smallest bucket); results
    are generated token lists.  A swap cuts the routing pointer
    between SEQUENCES and drains this server: in-flight decode
    sequences finish on the old version — never a mid-sequence
    version change — and any sequence outliving the drain budget is
    finished with the structured shutdown rejection at close
    (evict-and-resubmit on the new version is the caller's move);
    both counts are reported on the swap event.
    """

    #: host/server kwargs that map onto the GenerativeServer (the
    #: dense-server knobs like coalesce_ms are dropped, not errors:
    #: one replica process serves both artifact classes)
    _GEN_KW = ("slots", "page_tokens", "pool_budget", "kv_dtype",
               "agreement_floor", "slo_ms", "queue_depth",
               "breaker_limit", "evict_after_ms", "eos_id", "max_new",
               "kv_gate")

    generative = True

    def __init__(self, path, name="model", **kw):
        from .. import deploy
        from .generate import GenerativeServer

        params, gen = deploy.load_generative(path)
        # the npz payload deserializes to numpy; the decode programs
        # index the embed table with traced token ids, so params must
        # live as device arrays
        import jax

        params = jax.tree_util.tree_map(jax.numpy.asarray, params)
        srv_kw = {k: v for k, v in kw.items() if k in self._GEN_KW}
        buckets = tuple(int(b) for b in
                        (gen.get("prompt_buckets") or (4, 8, 16)))
        max_new = int(srv_kw.pop("max_new", gen.get("max_new", 16)))
        self._srv = GenerativeServer(
            params=params, vocab=int(gen["vocab"]),
            layers=int(gen["layers"]), heads=int(gen["heads"]),
            head_dim=int(gen["head_dim"]), prompt_buckets=buckets,
            max_new=max_new, name=name, **srv_kw)
        self.name = name
        #: warm-probe signature (ModelHost.swap probes
        #: ``zeros(item_shape, dtype)``)
        self.item_shape = (buckets[0],)
        self.dtype = onp.int32
        self._suppress_health_gauges = True

    def start(self, warm=True):
        self._srv.start(warm=warm)
        return self

    def submit(self, x, deadline_ms=None):
        toks = [int(t) for t in onp.asarray(x).reshape(-1)]
        return self._srv.submit(toks, deadline_ms=deadline_ms)

    def in_flight(self):
        return self._srv.in_flight()

    def report(self):
        return self._srv.report()

    @property
    def stats(self):
        st = {k: (dict(v) if isinstance(v, dict) else v)
              for k, v in self._srv.stats.items()}
        # the host's metrics aggregation reads the dense counter
        # names; a generative "batch" is one prefill dispatch
        st.setdefault("batches", st.get("prefills", 0))
        return st

    def health(self):
        s = self._srv
        with s._lock:
            live = bool(s._started and not s._stop)
            ready = bool(live and not s._draining
                         and not s._breaker_open)
            return {"ready": ready, "live": live,
                    "queue_depth": len(s._queue),
                    "inflight": s.in_flight()}

    def drain(self, timeout=30.0):
        return self._srv.drain(timeout=timeout)

    def close(self):
        self._srv.close()


class ModelHost:
    """Multi-model residency on one serving replica, HBM-budgeted.

    ``hbm_budget_mb`` (None = ``MXNET_FLEET_HBM_BUDGET_MB``; 0 =
    unlimited) bounds the summed reserved bytes of every resident
    model; :meth:`load` refuses past it with a structured
    ``ServeRejected(reason='hbm_budget')`` — a loud admission verdict,
    never an OOM mid-batch.  :meth:`swap` performs the zero-downtime
    rolling upgrade of ONE model: the budget gates the incoming
    artifact against the OTHER residents (the swapped model's old and
    new programs briefly co-reside by design — leave one model's
    headroom when budgeting a host that swaps under load).
    """

    def __init__(self, hbm_budget_mb=None, server_kw=None):
        from ..config import get_env

        mb = float(hbm_budget_mb if hbm_budget_mb is not None
                   else get_env("MXNET_FLEET_HBM_BUDGET_MB"))
        self.budget_bytes = int(mb * (1 << 20)) if mb > 0 else 0
        self._server_kw = dict(server_kw or {})
        self._lock = threading.RLock()
        self._models = {}     # name -> live ModelServer
        self._reserved = {}   # name -> reserved bytes
        self._paths = {}      # name -> artifact path
        self._info = {}       # name -> artifact_info header metadata
        self._load_kw = {}    # name -> per-model load() overrides
        self._pending = {}    # name -> reserved bytes mid-load/swap
        self.stats = {"loads": 0, "hbm_rejected": 0, "swaps": 0,
                      "rollbacks": 0, "unloads": 0}

    # ------------------------------------------------------ residency
    def used_bytes(self, exclude=None):
        """Resident + in-admission bytes (concurrent loads reserve
        BEFORE they start, so two admits cannot both squeeze past the
        budget)."""
        with self._lock:
            return sum(v for k, v in self._reserved.items()
                       if k != exclude) + \
                sum(v for k, v in self._pending.items()
                    if k != exclude)

    def residency(self):
        """Per-model reserved bytes vs the budget.  With the budget
        unlimited (0) the sizing compile is skipped entirely and
        every model reports 0 reserved bytes."""
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes or None,
                "used_bytes": self.used_bytes(),
                "models": {
                    name: {
                        "reserved_bytes": self._reserved[name],
                        "path": self._paths[name],
                        # round 18: the artifact header's identity —
                        # an operator (or the swap admission below)
                        # tells an int8 artifact from fp32 without
                        # deserializing any program
                        "quantized": (self._info.get(name) or
                                      {}).get("quantized"),
                        "param_dtypes": (self._info.get(name) or
                                         {}).get("param_dtypes"),
                    }
                    for name in sorted(self._models)},
            }

    def _admit_locked(self, name, reserved, exclude=None):
        """Budget-gate + reservation, atomically: a passing admit
        records ``reserved`` under ``_pending`` so a concurrent admit
        sees it.  Caller must hold the lock."""
        used = self.used_bytes(exclude=exclude)
        if self.budget_bytes and used + reserved > self.budget_bytes:
            self.stats["hbm_rejected"] += 1
            ModelServer._telemetry_event(
                "fleet_model_reject", model=name, reserved=reserved,
                resident=used, budget=self.budget_bytes)
            raise ServeRejected(
                "hbm_budget",
                f"model {name!r} reserves {reserved} bytes; "
                f"{used} bytes already resident of a "
                f"{self.budget_bytes}-byte host budget")
        self._pending[name] = reserved

    def _size_artifact(self, path, info):
        """Reserved-bytes sizing for admission: the exported-program
        memory analysis for a dense artifact, the summed param bytes
        for a generative one (its programs only build at start).
        With the budget unlimited (the default) the sizing read gates
        nothing — skipped entirely, admit at 0 bytes."""
        if not self.budget_bytes:
            return 0, None
        if (info or {}).get("generative"):
            from .. import deploy

            params, _ = deploy.load_generative(path)
            flat = deploy._flatten_params(params)
            return sum(int(onp.asarray(a).nbytes)
                       for a in flat.values()), None
        return artifact_reserved_bytes(path)

    def _make_server(self, name, path, info, exp, kw):
        """Construct (not started) the server class the artifact's
        header identity asks for — a GenerativeServer adapter for a
        ``"generative": true`` export, the dense ModelServer
        otherwise.  One replica process serves both classes."""
        if (info or {}).get("generative"):
            return GenerativeHostServer(path, name=name,
                                        **{**self._server_kw, **kw})
        return ModelServer.from_artifact(
            path, exported=exp, name=name,
            **{**self._server_kw, **kw})

    def load(self, name, path, **kw):
        """Admit + start one artifact (budget-gated); returns the live
        server.  The admission read doubles as the warm handle: the
        server below re-verifies the CRC on its own load, so a torn
        artifact fails HERE, before anything is evicted or started."""
        info = _artifact_identity(path)
        reserved, exp = self._size_artifact(path, info)
        with self._lock:
            # name-claim + budget reservation in ONE lock scope: two
            # concurrent loads of the same name (or two models racing
            # the last budget bytes) cannot both pass
            if name in self._models or name in self._pending:
                raise MXNetError(f"model {name!r} already resident "
                                 "(use swap for an upgrade)")
            self._admit_locked(name, reserved)
        try:
            srv = self._make_server(name, path, info, exp, kw)
            srv._suppress_health_gauges = True  # the host aggregates
            srv.start(warm=True)
        except BaseException:
            with self._lock:
                self._pending.pop(name, None)
            raise
        with self._lock:
            self._pending.pop(name, None)
            self._models[name] = srv
            self._reserved[name] = reserved
            self._paths[name] = str(path)
            self._info[name] = info
            self._load_kw[name] = dict(kw)  # swaps must keep these
            self.stats["loads"] += 1
        ModelServer._telemetry_event(
            "fleet_model_load", model=name, reserved=reserved,
            resident=self.used_bytes(), budget=self.budget_bytes)
        return srv

    def unload(self, name):
        with self._lock:
            if name in self._pending:
                raise MXNetError(
                    f"model {name!r} has a load/swap in flight — "
                    "retry the unload once it resolves")
            srv = self._models.pop(name, None)
            self._reserved.pop(name, None)
            self._paths.pop(name, None)
            self._info.pop(name, None)
            self._load_kw.pop(name, None)
        if srv is None:
            raise MXNetError(f"model {name!r} not resident "
                             f"(resident: {sorted(self._models)})")
        srv.drain(timeout=10.0)
        srv.close()
        with self._lock:
            self.stats["unloads"] += 1
        ModelServer._telemetry_event("fleet_model_unload", model=name)

    def get(self, model=None):
        with self._lock:
            if model is None:
                if len(self._models) == 1:
                    return next(iter(self._models.values()))
                if "model" in self._models:
                    return self._models["model"]
                raise MXNetError(
                    "multi-model host needs an explicit model name "
                    f"(resident: {sorted(self._models)})")
            srv = self._models.get(model)
            if srv is None:
                raise MXNetError(
                    f"unknown model {model!r} "
                    f"(resident: {sorted(self._models)})")
            return srv

    # ------------------------------------------------------- serving
    def submit(self, x, deadline_ms=None, model=None):
        return self.get(model).submit(x, deadline_ms=deadline_ms)

    # ---------------------------------------------------------- swap
    def swap(self, model, path, probe_timeout=60.0):
        """Zero-downtime model swap: load ``path`` beside the live
        server, warm it, require ONE finite probe answer, then cut the
        routing pointer over between batches and drain the old server.
        Any failure before the cutover closes the new server and
        KEEPS the old one serving (rollback) — raised as MXNetError so
        the caller knows the fleet still runs the previous artifact.
        Returns the swap wall time in milliseconds."""
        faultsim.inject("fleet.swap")
        t0 = time.perf_counter()
        with self._lock:
            old = self.get(model)
            name = old.name
            if name in self._pending:
                raise MXNetError(
                    f"model {name!r} already has a load/swap in "
                    "flight")
            # claim the name NOW (zero bytes while the artifact is
            # sized): a concurrent load/swap/unload of it refuses
            # until this swap resolves — without the claim, an unload
            # landing during the warm probe would be silently
            # resurrected by the cutover below
            self._pending[name] = 0
            kw = dict(self._load_kw.get(name, {}))
        info = _artifact_identity(path)
        new = None
        try:
            # unlimited budget skips the sizing compile — it would sit
            # on the critical path of exactly the swap latency this
            # feature exists to minimize, gating nothing
            reserved, exp = self._size_artifact(path, info)
            with self._lock:
                # exclude=name: the swapped model's old and new
                # programs briefly co-reside by design (module
                # docstring); the reservation still blocks
                # CONCURRENT admits
                self._pending.pop(name)
                self._admit_locked(name, reserved, exclude=name)
            # per-model load() overrides (slo_ms, queue bounds, ...)
            # survive the upgrade — a swap changes the ARTIFACT, not
            # the model's admission contract
            new = self._make_server(name, path, info, exp, kw)
            new._suppress_health_gauges = True  # the host aggregates
            new.start(warm=True)
            probe = onp.zeros(new.item_shape, new.dtype)
            out = new.submit(probe).result(timeout=probe_timeout)
            out = onp.asarray(out)
            if onp.issubdtype(out.dtype, onp.floating) \
                    and not onp.isfinite(out).all():
                raise MXNetError("warm probe returned non-finite "
                                 "outputs")
        except Exception as exc:
            if isinstance(exc, ServeRejected) \
                    and exc.reason == "hbm_budget":
                # the budget refusal never touched the live model:
                # structured passthrough, not a rollback.  Every
                # OTHER ServeRejected here came from the NEW server's
                # warm probe (a non-finite output rejects the probe
                # request) — that IS a failed swap attempt
                with self._lock:
                    self._pending.pop(name, None)
                raise
            if new is not None:
                new.close()
            with self._lock:
                self._pending.pop(name, None)
                self.stats["rollbacks"] += 1
            ModelServer._telemetry_event(
                "fleet_swap_rollback", model=name, path=str(path),
                error=repr(exc))
            raise SwapRolledBack(
                f"swap of {name!r} to {path!r} rolled back "
                f"({exc}); the previous artifact keeps serving") \
                from exc
        # cutover between batches: new submits route to the new
        # server the moment the pointer moves; the old server's
        # in-flight batches finish in its drain
        with self._lock:
            self._pending.pop(name, None)
            self._models[name] = new
            self._reserved[name] = reserved
            self._paths[name] = str(path)
            self._info[name] = info
            self.stats["swaps"] += 1
        gen_extra = {}
        if getattr(old, "generative", False):
            # the satellite-2 contract: in-flight decode sequences at
            # cutover ride out on the OLD version (no mid-sequence
            # version change); whether they all finished inside the
            # drain budget is REPORTED, never assumed — stragglers
            # are finished with the structured shutdown rejection at
            # close and may re-prefill on the new version
            gen_extra["gen_inflight_at_cutover"] = old.in_flight()
        drained = old.drain(timeout=30.0)
        if gen_extra:
            gen_extra["gen_drained"] = bool(drained)
            gen_extra["gen_inflight_at_close"] = old.in_flight()
        old.close()
        swap_ms = (time.perf_counter() - t0) * 1e3
        try:
            from .. import telemetry

            telemetry.count("fleet_swaps")
        except Exception:
            pass
        ModelServer._telemetry_event(
            "fleet_swap", model=name, path=str(path),
            swap_ms=round(swap_ms, 3), reserved=reserved, **gen_extra)
        return swap_ms

    # -------------------------------------------------------- health
    def health(self):
        with self._lock:
            servers = dict(self._models)
        per = {name: srv.health() for name, srv in servers.items()}
        ready = bool(per) and all(h["ready"] for h in per.values())
        live = bool(per) and all(h["live"] for h in per.values())
        payload = {
            "ready": ready, "live": live,
            "queue_depth": sum(h["queue_depth"] for h in per.values()),
            "inflight": sum(h["inflight"] for h in per.values()),
            "models": per,
        }
        # the host's AGGREGATE is the replica's probe truth: it wins
        # over the per-server writes health() just made
        ModelServer._telemetry_gauge("serve_ready", int(ready))
        ModelServer._telemetry_gauge("serve_live", int(live))
        return payload

    def metrics_text(self):
        from .frontend import _metrics_text

        with self._lock:
            servers = dict(self._models)
        h = self.health()
        counters = {"serve_requests": 0, "serve_shed": 0,
                    "serve_batches": 0, "serve_breaker_trips": 0}
        for srv in servers.values():
            counters["serve_requests"] += srv.stats["requests"]
            counters["serve_shed"] += srv.stats["shed"]
            counters["serve_batches"] += srv.stats["batches"]
            counters["serve_breaker_trips"] += \
                srv.stats["breaker_trips"]
        return _metrics_text(
            h["ready"], h["live"], counters,
            gauges={"serve_queue_depth": h["queue_depth"],
                    "serve_inflight": h["inflight"]})

    # ------------------------------------------------------ lifecycle
    def drain_all(self, timeout=30.0):
        with self._lock:
            servers = list(self._models.values())
        return all(srv.drain(timeout=timeout) for srv in servers)

    def close_all(self):
        with self._lock:
            servers = list(self._models.values())
            self._models.clear()
            self._reserved.clear()
            self._paths.clear()
        for srv in servers:
            srv.close()


# ======================================================== the router
class _Failover(Exception):
    """One routing attempt failed in a way a sibling can absorb."""


class _Replica:
    __slots__ = ("idx", "addr", "port", "proc", "state", "last_health",
                 "outstanding", "routed", "port_file", "probe_misses",
                 "log_path", "t_spawn")

    def __init__(self, idx, addr=None, port=None, proc=None,
                 port_file=None, log_path=None):
        self.idx = idx
        self.addr = addr or "127.0.0.1"
        self.port = port
        self.proc = proc
        self.port_file = port_file
        self.log_path = log_path
        self.t_spawn = time.monotonic()
        self.state = "starting" if port is None else "ready"
        self.last_health = {}
        self.outstanding = 0
        self.routed = 0
        self.probe_misses = 0

    @property
    def live(self):
        return self.state not in ("dead", "drained")


class FleetRouter:
    """Fault-tolerant front over N replica serving processes (module
    docstring).  Replicas are HTTP endpoints — either spawned worker
    processes (:meth:`spawn`) or endpoints attached by the caller
    (in-process frontends in tests, remote hosts in deployment).

    Parameters
    ----------
    endpoints : iterable of (addr, port)
        Pre-existing replicas to attach (not lifecycle-managed).
    slo_ms : float
        Fleet-level default deadline (None = ``MXNET_SERVE_SLO_MS``).
    probe_interval : float
        Seconds between health-probe sweeps.
    scale_up_depth / scale_down_depth / min_replicas / max_replicas
        Queue-depth-EWMA autoscaler: when the EWMA of per-ready-replica
        queue depth crosses ``scale_up_depth`` a replica is spawned
        (the round-12 resize, reshard-not-restart); below
        ``scale_down_depth`` one is SIGTERM-drained.  ``scale_up_depth
        None`` disables autoscaling (``resize()`` stays available).
    scale_ewma : float
        EWMA smoothing factor (None = ``MXNET_FLEET_SCALE_EWMA``).
    scale_cooldown_s : float
        Minimum seconds between autoscale decisions — a replica being
        spawned must get a chance to absorb load before the EWMA can
        demand another.
    """

    def __init__(self, endpoints=(), *, slo_ms=None,
                 probe_interval=0.25, scale_up_depth=None,
                 scale_down_depth=None, min_replicas=1,
                 max_replicas=8, scale_ewma=None,
                 scale_cooldown_s=10.0, name="fleet"):
        from ..config import get_env

        self.name = str(name)
        self.slo_ms = float(slo_ms if slo_ms is not None
                            else get_env("MXNET_SERVE_SLO_MS"))
        self.probe_interval = float(probe_interval)
        self._alpha = float(scale_ewma if scale_ewma is not None
                            else get_env("MXNET_FLEET_SCALE_EWMA"))
        self.scale_up_depth = scale_up_depth
        self.scale_down_depth = scale_down_depth
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_cooldown_s = float(scale_cooldown_s)
        #: bring-up budget for ANY spawned replica (autoscale/resize
        #: spawns included, not just the initial _wait_ready): one
        #: wedged 'starting' child must not pause the autoscaler
        #: forever.  spawn() overrides it with its ready_timeout.
        self.bringup_timeout = 120.0
        self._lock = threading.RLock()
        self._replicas = []
        self._next_idx = 0
        self._spawn_spec = None
        self._dir = None
        self._stop = threading.Event()
        self._probe_thread = None
        self._probe_n = 0
        self._last_scale = 0.0
        self.queue_ewma = 0.0
        #: last artifact the WHOLE fleet committed to (rollback
        #: target of a refused rolling swap) and its header
        #: model_version (the freshness-monotonicity floor) — None
        #: until a spawn/swap stamps them
        self._prev_artifact = None
        self._committed_version = None
        self.stats = {"requests": 0, "completed": 0, "shed": 0,
                      "failovers": 0, "ejected": 0, "resizes": 0,
                      "swaps": 0, "swap_rollbacks": 0}
        for addr, port in endpoints:
            self._replicas.append(_Replica(self._next_idx, addr=addr,
                                           port=int(port)))
            self._next_idx += 1

    # ---------------------------------------------------- spawn mode
    @classmethod
    def spawn(cls, artifact, replicas=None, *, model="model",
              env=None, replica_env=None, runlog_dir=None,
              hbm_budget_mb=None, ready_timeout=120.0,
              coalesce_ms=1.0, drain_timeout=30.0, **kw):
        """Launch ``replicas`` worker processes serving ``artifact``
        (the fleet's lifecycle-managed mode) and return the router
        once every replica reports ready.

        ``env`` merges into every replica's environment;
        ``replica_env`` is ``{idx: {...}}`` per-replica overrides (the
        drills arm ``MXNET_FAULT_SPEC`` on exactly one replica this
        way); ``runlog_dir`` arms ``MXNET_RUNLOG`` per replica at
        ``<dir>/replica-<idx>.jsonl`` so the drill can assert each
        replica's retrace counter."""
        from ..config import get_env

        n = int(replicas if replicas is not None
                else get_env("MXNET_FLEET_REPLICAS"))
        if n < 1:
            raise MXNetError(f"fleet needs >= 1 replica, got {n}")
        router = cls(**kw)
        router.bringup_timeout = float(ready_timeout)
        router._dir = tempfile.mkdtemp(prefix="mxnet_tpu_fleet_")
        router._spawn_spec = {
            "artifact": str(artifact), "model": str(model),
            "env": dict(env or {}),
            "replica_env": {int(k): dict(v) for k, v in
                            (replica_env or {}).items()},
            "runlog_dir": str(runlog_dir) if runlog_dir else None,
            "hbm_budget_mb": hbm_budget_mb,
            "coalesce_ms": float(coalesce_ms),
            "drain_timeout": float(drain_timeout),
        }
        router._prev_artifact = str(artifact)
        v = (_artifact_identity(artifact) or {}).get("model_version")
        if v is not None:
            router._committed_version = int(v)
        try:
            for _ in range(n):
                router._spawn_replica()
            router._wait_ready(ready_timeout)
        except BaseException:
            # a half-up fleet must not leak worker processes
            router.close(timeout=10.0)
            raise
        router.start_probes()
        return router

    def _spawn_replica(self):
        spec = self._spawn_spec
        if spec is None:
            raise MXNetError(
                "this router attached existing endpoints — it cannot "
                "spawn replicas (use FleetRouter.spawn for a "
                "lifecycle-managed fleet)")
        with self._lock:
            idx = self._next_idx
            self._next_idx += 1
        port_file = os.path.join(self._dir, f"replica-{idx}.port")
        log_path = os.path.join(self._dir, f"replica-{idx}.log")
        cmd = [sys.executable, "-m", "mxnet_tpu.serving.fleet",
               "--artifact", f"{spec['model']}={spec['artifact']}",
               "--port", "0", "--port-file", port_file,
               "--slo-ms", str(self.slo_ms),
               "--coalesce-ms", str(spec["coalesce_ms"]),
               "--drain-timeout", str(spec["drain_timeout"])]
        if spec["hbm_budget_mb"] is not None:
            cmd += ["--hbm-budget-mb", str(spec["hbm_budget_mb"])]
        env = dict(os.environ)
        # a parent's armed fault spec must not leak into every child
        # (drills arm replicas EXPLICITLY via env/replica_env) — and
        # neither may its telemetry sinks: N replicas appending into
        # the parent's run log breaks the one-run-per-file contract,
        # and each child's change-triggered textfile rewrite would
        # clobber the parent's.  runlog_dir is the per-replica
        # replacement; env/replica_env can still opt a child in.
        for leak in ("MXNET_FAULT_SPEC", "MXNET_RUNLOG",
                     "MXNET_METRICS_TEXTFILE"):
            env.pop(leak, None)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in [os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                env.get("PYTHONPATH")] if p)
        # round 20: identity + trace stamp BEFORE env/replica_env so a
        # drill can still override them.  The child's run_start carries
        # role=replica/rank=idx and its spans parent onto this spawn.
        _tracing.stamp_env(env, "replica", rank=idx)
        env.update(spec["env"])
        if spec["runlog_dir"]:
            env["MXNET_RUNLOG"] = os.path.join(
                spec["runlog_dir"], f"replica-{idx}.jsonl")
        env.update(spec["replica_env"].get(idx, {}))
        logf = open(log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, stdout=logf, stderr=logf,
                                    env=env)
        finally:
            logf.close()  # the child holds its own fd
        rep = _Replica(idx, proc=proc, port_file=port_file,
                       log_path=log_path)
        with self._lock:
            self._replicas.append(rep)
        self._telemetry_event("fleet_spawn", replica=idx,
                              pid=proc.pid)
        return rep

    def _wait_ready(self, timeout):
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            self._probe_once(record=False)
            with self._lock:
                pending = [r for r in self._replicas
                           if r.live and r.state != "ready"]
                dead = [r for r in self._replicas
                        if r.state == "dead"]
            if not pending:
                if dead:
                    # ALL-or-error: a replica dying at bring-up must
                    # not silently hand the caller a smaller fleet
                    # than it asked for (resize() raises the same way)
                    raise MXNetError(
                        f"{len(dead)} replica(s) died during "
                        "bring-up " + self._death_report(dead))
                return
            time.sleep(0.1)
        raise MXNetError(
            f"fleet not ready within {timeout}s "
            + self._death_report([r for r in self._replicas
                                  if r.state != "ready"]))

    def _death_report(self, reps):
        notes = []
        for r in reps:
            rc = r.proc.poll() if r.proc else None
            tail = ""
            if r.log_path and os.path.exists(r.log_path):
                with open(r.log_path, "rb") as f:
                    tail = f.read()[-800:].decode(errors="replace")
            notes.append(f"replica {r.idx} state={r.state} rc={rc} "
                         f"log: ...{tail}")
        return "; ".join(notes) or "(no replicas)"

    # --------------------------------------------------- health probe
    def start_probes(self):
        if self._probe_thread is not None:
            return self
        self._stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop,
            name=f"mxnet_tpu-fleet-probe-{self.name}", daemon=True)
        self._probe_thread.start()
        return self

    def _probe_loop(self):
        while not self._stop.is_set():
            try:
                self._probe_once()
            except Exception:  # noqa: BLE001 — the probe loop is the
                pass           # router's heartbeat; it must not die
            self._stop.wait(self.probe_interval)

    def _probe_once(self, record=True):
        with self._lock:
            reps = [r for r in self._replicas if r.live]
        # probe CONCURRENTLY: serial sweeps would let one wedged
        # replica (accepts TCP, never answers — the 2 s per-probe
        # timeout) stall failure detection and the autoscaler signal
        # for the whole fleet
        threads = [threading.Thread(target=self._probe_replica,
                                    args=(rep,), daemon=True)
                   for rep in reps]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)  # > the 2 s http timeout: only a
            #                      truly stuck probe is abandoned
        # ---- the autoscaler's signal: queue depth per ready replica
        with self._lock:
            ready = [r for r in self._replicas if r.state == "ready"]
            depth = sum(r.outstanding
                        + int(r.last_health.get("queue_depth", 0))
                        for r in ready)
            x = depth / max(1, len(ready))
            self.queue_ewma = (self._alpha * x
                               + (1.0 - self._alpha) * self.queue_ewma)
            self._probe_n += 1
            sampled = record and self._probe_n % 20 == 0
        if record:
            # bring-up sweeps (_wait_ready) must not autoscale a
            # fleet that has not finished converging
            self._maybe_scale()
        if sampled:
            self._fleet_record("probe")

    def _probe_replica(self, rep):
        """One replica's health sweep (runs on a short-lived probe
        thread — every exit path just returns)."""
        if rep.proc is not None and rep.proc.poll() is not None:
            rc = rep.proc.poll()
            if rep.state == "draining":
                # a drained scale-down/close exit is CLEAN: the
                # router stopped routing before the SIGTERM
                rep.state = "drained"
                self._telemetry_event("fleet_drained",
                                      replica=rep.idx, rc=rc)
            else:
                self._eject(rep, f"process exited rc={rc}")
            return
        if rep.state == "starting" and time.monotonic() \
                - rep.t_spawn > self.bringup_timeout:
            # a child alive but wedged in bring-up (never wrote its
            # port file): without this budget it would pause
            # _maybe_scale's 'starting' gate forever
            if rep.proc is not None:
                try:
                    rep.proc.kill()
                except OSError:
                    pass
            self._eject(rep, "bring-up timed out after "
                             f"{self.bringup_timeout}s")
            return
        if rep.port is None and not self._read_port(rep):
            return
        try:
            status, health = http_call(rep.addr, rep.port, "GET",
                                       "/healthz", timeout=2.0)
        except Exception:  # connection refused / reset / timeout
            rep.probe_misses += 1
            self._bench(rep)
            # an attached endpoint (no proc to poll) that misses
            # several probes in a row is gone — eject it like a
            # dead process
            if rep.probe_misses >= 4 and rep.proc is None:
                self._eject(rep, "endpoint unreachable")
            return
        rep.probe_misses = 0
        if isinstance(health, dict):
            rep.last_health = health
        with self._lock:
            # check-and-promote under the lock: _drain_one flips to
            # 'draining' under it, and an unlocked promotion here
            # could resurrect a SIGTERM'd replica into the routing
            # pool mid-scale-down
            if rep.state in ("starting", "ready", "unready"):
                rep.state = "ready" if status == 200 else "unready"

    def _read_port(self, rep):
        pf = rep.port_file
        if not pf or not os.path.exists(pf):
            return False
        try:
            with open(pf) as f:
                rep.port = int(f.read().strip())
        except (OSError, ValueError):
            return False
        return True

    def _ready_replicas(self):
        with self._lock:
            return [r for r in self._replicas if r.state == "ready"]

    def _bench(self, rep):
        """Pull a replica out of the routing pool until a health probe
        re-verifies it — WITHOUT clobbering a draining/dead state (a
        scale-down drain must still be recorded as drained, not
        ejected).  Check-and-set under the lock: _drain_one flips
        'ready' -> 'draining' under it, and an unlocked write here
        could land after that flip."""
        with self._lock:
            if rep.state == "ready":
                rep.state = "unready"

    def _eject(self, rep, why):
        with self._lock:
            # guard-and-set under the lock: a dying replica is often
            # observed by several submit threads AND the probe loop
            # at once — exactly one of them ejects.  A replica the
            # probe loop already recorded as cleanly DRAINED must not
            # be re-counted as an ejection by a straggling request
            if rep.state in ("dead", "drained"):
                return
            rep.state = "dead"
            self.stats["ejected"] += 1
        self._telemetry_event("fleet_eject", replica=rep.idx,
                              reason=str(why))
        self._fleet_record("eject")

    # -------------------------------------------------------- routing
    def submit(self, x, deadline_ms=None, model=None):
        """Route one request (returns the output row as numpy).  Sheds
        raise :class:`ServeRejected` — structured, like the in-process
        server.  A replica that fails mid-request (death, open
        breaker, drain) is ejected/benched and the request retries on
        a sibling INSIDE its original deadline
        (``retry_call(deadline_sec=)``)."""
        faultsim.inject("fleet.route")
        budget_ms = self.slo_ms if deadline_ms is None \
            else float(deadline_ms)
        deadline = time.monotonic() + budget_ms / 1e3
        x = onp.asarray(x)
        with self._lock:
            self.stats["requests"] += 1
        self._telemetry_count("fleet_requests")
        # round-20 trace root: one fleet_request span per submit when
        # telemetry is armed (or a caller-bound context exists); each
        # routing attempt sends a child hop in the traceparent header
        # so the replica's spans link back here
        req_ctx = t_req0 = None
        if _tracing.enabled() or _tracing.current_context() is not None:
            parent = _tracing.current_context()
            req_ctx = parent.child() if parent is not None \
                else _tracing.mint()
            t_req0 = time.perf_counter()
        last = {"reason": "no_replica",
                "detail": "no ready replica to route to",
                "failover": False}
        tried = set()

        def attempt():
            if last["failover"]:
                # the PREVIOUS attempt's replica failure is being
                # retried now — count the failover at the start of
                # the retry, not in on_retry: retry_call may call
                # on_retry and then still give up on the deadline
                # without ever dispatching to a sibling
                with self._lock:
                    self.stats["failovers"] += 1
                self._telemetry_count("fleet_failovers")
            last["failover"] = False
            rep = self._pick(exclude=tried)
            if rep is None:
                raise _Failover
            tried.add(rep.idx)
            remaining_ms = (deadline - time.monotonic()) * 1e3
            if remaining_ms <= 0:
                last.update(reason="deadline",
                            detail="fleet budget exhausted before "
                                   "dispatch")
                raise _Failover
            hop = hdrs = t_hop0 = None
            if req_ctx is not None:
                hop = req_ctx.child()
                hdrs = {_tracing.TRACEPARENT_HEADER: hop.to_header()}
                t_hop0 = time.perf_counter()
            with self._lock:
                rep.outstanding += 1
            try:
                status, body = http_call(
                    rep.addr, rep.port, "POST", "/v1/predict",
                    body={"inputs": [x.tolist()],
                          "deadline_ms": remaining_ms,
                          "model": model},
                    timeout=remaining_ms / 1e3 + 5.0,
                    headers=hdrs)
            except Exception as exc:  # connection-level death
                if rep.proc is not None \
                        and rep.proc.poll() is not None:
                    # a DRAINING/DRAINED replica exiting is the clean
                    # scale-down path — the probe loop records it as
                    # drained; only an unexpected death ejects
                    if rep.state not in ("draining", "drained"):
                        self._eject(rep,
                                    f"died mid-request rc="
                                    f"{rep.proc.poll()}")
                else:
                    self._bench(rep)  # probe re-verifies
                last.update(reason="model_error",
                            detail=f"replica {rep.idx}: {exc!r}",
                            failover=True)
                raise _Failover from exc
            finally:
                with self._lock:
                    rep.outstanding -= 1
                    rep.routed += 1
            if status == 200:
                if hop is not None:
                    _tracing.emit_span("route_attempt", t_hop0,
                                       time.perf_counter(), hop,
                                       kind="client",
                                       replica=int(rep.idx))
                return onp.asarray(body["outputs"][0])
            reason = body.get("error", "model_error") \
                if isinstance(body, dict) else "model_error"
            detail = body.get("detail", "") \
                if isinstance(body, dict) else str(body)[:200]
            if reason in ("breaker_open", "draining", "shutdown"):
                # not routable until a probe says otherwise — the
                # ejection contract for an opened breaker
                self._bench(rep)
            last.update(reason=reason,
                        detail=f"replica {rep.idx}: {detail}",
                        # a REPLICA failure (died, 500, benched) is a
                        # failover when retried; queue_full/deadline/
                        # expired are back-pressure sheds, not replica
                        # failures — counting them would mask the real
                        # signal this metric exists for
                        failover=reason in ("model_error",
                                            "breaker_open",
                                            "draining", "shutdown"))
            raise _Failover

        with self._lock:
            n_live = sum(1 for r in self._replicas if r.live)
        try:
            out = retry_call(
                attempt, retry_on=(_Failover,),
                attempts=max(2, n_live + 1), base_delay=0.005,
                max_delay=0.05, jitter=0.2,
                deadline_sec=max(0.01,
                                 deadline - time.monotonic()))
        except _Failover:
            with self._lock:
                self.stats["shed"] += 1
            self._telemetry_count("fleet_shed")
            raise ServeRejected(last["reason"], last["detail"]) \
                from None
        with self._lock:
            self.stats["completed"] += 1
        if req_ctx is not None:
            _tracing.emit_span("fleet_request", t_req0,
                               time.perf_counter(), req_ctx,
                               kind="server", model=str(model or ""))
        return out

    def _pick(self, exclude=()):
        """Least-queue-depth routing: the ready replica with the
        fewest (router-local outstanding + last-probed queued)
        requests.  ``exclude`` holds replicas already tried for THIS
        request; when every ready replica has been tried the exclusion
        resets (a second try beats a shed)."""
        with self._lock:
            ready = [r for r in self._replicas if r.state == "ready"]
            fresh = [r for r in ready if r.idx not in exclude]
            pool = fresh or ready
            if not pool:
                return None
            return min(pool, key=lambda r: (
                r.outstanding
                + int(r.last_health.get("queue_depth", 0))))

    # ---------------------------------------------------- autoscaling
    def _maybe_scale(self):
        if self.scale_up_depth is None or self._spawn_spec is None:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_scale < self.scale_cooldown_s:
                return
            live = [r for r in self._replicas
                    if r.live and r.state != "draining"]
            n = len(live)
            ready_n = sum(1 for r in live if r.state == "ready")
            ewma = self.queue_ewma
            if any(r.state == "starting" for r in live):
                # a spawned replica is still converging: give it a
                # chance to absorb (or shed) load before the EWMA can
                # demand another decision either way
                return
        if ewma > float(self.scale_up_depth) \
                and n < self.max_replicas:
            self._spawn_replica()
            with self._lock:
                self._last_scale = now
            self._resize_event(n, n + 1, "queue_ewma_high")
        elif self.scale_down_depth is not None \
                and ewma < float(self.scale_down_depth) \
                and ready_n > self.min_replicas:
            # the floor counts ROUTABLE replicas: with a sibling
            # benched (open breaker, missed probes) the drain would
            # take the only ready replica and the fleet would shed
            # 'no_replica' — exactly what scale-down must never do
            # the event is emitted only for a drain that actually
            # started — a momentarily-empty ready pool must not
            # record a resize that never happened
            if self._drain_one() is not None:
                with self._lock:
                    self._last_scale = now
                self._resize_event(n, n - 1, "queue_ewma_low")

    def resize(self, n):
        """Explicit resize to ``n`` replicas (the autoscaler's manual
        twin): spawns or SIGTERM-drains one replica at a time, routing
        untouched throughout."""
        n = int(n)
        if self._spawn_spec is None:
            raise MXNetError("resize needs a spawned fleet")
        if not self.min_replicas <= n <= self.max_replicas:
            raise MXNetError(
                f"resize to {n} outside the fleet bounds "
                f"[{self.min_replicas}, {self.max_replicas}]")
        spawns = 0
        while True:
            with self._lock:
                live = [r for r in self._replicas
                        if r.live and r.state != "draining"]
            if len(live) == n:
                return n
            if len(live) < n:
                if spawns >= n + 4:
                    # spawned children keep dying before counting as
                    # live (unreadable artifact, broken env): refuse
                    # with the evidence instead of churning processes
                    raise MXNetError(
                        f"resize to {n} gave up after {spawns} "
                        "spawn attempts: "
                        + self._death_report(
                            [r for r in self._replicas
                             if r.state == "dead"][-3:]))
                spawns += 1
                self._spawn_replica()
                self._resize_event(len(live), len(live) + 1,
                                   "explicit")
            else:
                if self._drain_one() is None:
                    # nothing ready to drain (every live replica is
                    # starting/unready): refuse rather than spin —
                    # the caller retries once the fleet converges
                    raise MXNetError(
                        f"cannot scale down to {n}: no ready replica "
                        f"to drain ({self.health()['per_replica']})")
                self._resize_event(len(live), len(live) - 1,
                                   "explicit")

    def _resize_event(self, old_n, new_n, trigger):
        """The round-12 composition: a serving resize is the SAME
        reshard-not-restart event training resizes emit — topology
        blocks diffed by ``reshard_verdict``, a ``resize`` run-log
        event, the ``reshards`` counter — so one dashboard reads both
        worlds."""
        from ..resilience import elastic

        verdict = elastic.reshard_verdict(
            elastic.topology_block(world_size=old_n,
                                   sharding="serving"),
            elastic.topology_block(world_size=new_n,
                                   sharding="serving"))
        with self._lock:
            self.stats["resizes"] += 1
        self._telemetry_count("fleet_resizes")
        self._telemetry_count("reshards")
        self._telemetry_event(
            "resize", old_world=old_n, new_world=new_n,
            reasons=verdict["reasons"], scope="serving_fleet",
            trigger=str(trigger),
            queue_ewma=round(self.queue_ewma, 3))
        self._fleet_record("resize")

    def _drain_one(self):
        """Scale down by one: the least-loaded ready replica leaves
        the routing pool FIRST, then gets SIGTERM — PreemptionDrain in
        the worker finishes its admitted work, so the fleet sheds
        nothing on the way down."""
        with self._lock:
            ready = [r for r in self._replicas if r.state == "ready"]
            if not ready:
                return None
            rep = min(ready, key=lambda r: r.outstanding)
            rep.state = "draining"
        self._telemetry_event("fleet_scale_down", replica=rep.idx)
        if rep.proc is not None:
            try:
                rep.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        return rep

    # --------------------------------------------------- rolling swap
    def _served_identity(self, rep, model=None, timeout=5.0):
        """One replica's served artifact path (via ``/v1/models``) —
        what the post-swap consistency assertion compares across the
        fleet.  None when the replica cannot answer."""
        try:
            status, body = http_call(rep.addr, rep.port, "GET",
                                     "/v1/models", timeout=timeout)
        except Exception:
            return None
        if status != 200 or not isinstance(body, dict):
            return None
        models = body.get("models") or {}
        if model is None and len(models) == 1:
            entry = next(iter(models.values()))
        else:
            entry = models.get(model or "model")
        return entry.get("path") if isinstance(entry, dict) else None

    def rolling_swap(self, path, model=None, probe_timeout=120.0):
        """Upgrade the whole fleet to the artifact at ``path`` one
        replica at a time — each replica loads the new program beside
        the live one, warm-probes it, and cuts over between batches
        while its siblings keep serving.

        Commit/rollback protocol (round 18): a replica that REFUSES
        its swap while alive (bad artifact / failed warm probe — the
        frontend's non-200 answer) aborts the rollout and rolls the
        already-swapped replicas BACK to the previous artifact, so a
        partial failure can never leave the fleet straddling two
        versions.  A replica that dies mid-swap (connection-level
        failure) is ejected and the rollout continues — its siblings
        upgrade and its traffic fails over, exactly as before.  When
        the new artifact's header carries a ``model_version``, a swap
        below the last fully-committed version is refused outright
        (freshness monotonicity).  The result reports per-replica
        timings/errors plus ``committed`` / ``rolled_back`` and the
        post-rollout ``identities`` consistency check (every live
        replica must answer with ONE artifact path)."""
        t0 = time.perf_counter()
        meta = _artifact_identity(path) or {}
        version = meta.get("model_version")
        # round 20: the v2 header's trace_anchor is the trainer's
        # export-span context — parenting the swap span on it links
        # the serve-side cutover back to the training step that
        # produced these weights, across processes and hosts
        swap_ctx = None
        if _tracing.enabled():
            anchor = _tracing.from_header(meta.get("trace_anchor"))
            if anchor is not None:
                swap_ctx = anchor.child()
            else:
                cur = _tracing.current_context()
                swap_ctx = cur.child() if cur is not None \
                    else _tracing.mint()
        with self._lock:
            committed_version = self._committed_version
            prev_path = self._prev_artifact
        if version is not None and committed_version is not None \
                and int(version) < int(committed_version):
            self._telemetry_event(
                "fleet_swap_refused", path=str(path),
                version=int(version),
                committed_version=int(committed_version),
                reason="version_regression")
            raise MXNetError(
                f"rolling swap to {path!r} (model_version {version}) "
                f"would regress the fleet below the last committed "
                f"version {committed_version} — refused")
        per, errors = {}, {}
        rolled_back = []
        abort = False
        with self._lock:
            # future spawns (autoscale, resize) must serve the NEW
            # artifact — the rolling swap changes the fleet's desired
            # state, not just the replicas alive right now
            if self._spawn_spec is not None:
                self._spawn_spec["artifact"] = str(path)
            # every live replica is a target, not just the ready
            # ones: a replica benched by one missed probe (or an open
            # breaker) rejoins the pool later and must NOT rejoin
            # serving the previous artifact
            targets = [r for r in self._replicas
                       if r.live and r.state != "draining"]
        for rep in targets:
            if rep.port is None:
                # spawned before the swap, not up yet: it may come up
                # on the previous artifact — surface it, never hide it
                errors[rep.idx] = ("replica still starting; spawned "
                                   "before the swap")
                continue
            try:
                status, body = http_call(
                    rep.addr, rep.port, "POST", "/admin/swap",
                    body={"model": model, "path": str(path)},
                    timeout=probe_timeout)
            except Exception as exc:  # mid-swap death
                errors[rep.idx] = repr(exc)
                if rep.proc is not None \
                        and rep.proc.poll() is not None:
                    self._eject(rep, f"died mid-swap "
                                     f"rc={rep.proc.poll()}")
                continue
            if status == 200:
                per[rep.idx] = body["swap_ms"]
            else:
                # the replica is ALIVE and refused: the artifact is
                # bad for every sibling too — abort the rollout and
                # roll the swapped prefix back to one version
                errors[rep.idx] = f"{status}: {body}"
                abort = True
                break
        if abort:
            with self._lock:
                if self._spawn_spec is not None and prev_path:
                    self._spawn_spec["artifact"] = str(prev_path)
                self.stats["swap_rollbacks"] += 1
            self._telemetry_count("fleet_swap_rollbacks")
            for rep in targets:
                if rep.idx not in per or not prev_path:
                    continue
                try:
                    status, body = http_call(
                        rep.addr, rep.port, "POST", "/admin/swap",
                        body={"model": model, "path": str(prev_path)},
                        timeout=probe_timeout)
                except Exception as exc:
                    errors[rep.idx] = f"rollback failed: {exc!r}"
                    continue
                if status == 200:
                    rolled_back.append(rep.idx)
                    del per[rep.idx]
                else:
                    errors[rep.idx] = (f"rollback failed: {status}: "
                                       f"{body}")
            self._telemetry_event(
                "fleet_rolling_swap_rollback", path=str(path),
                prev=str(prev_path), rolled_back=sorted(rolled_back),
                errors=errors)
            self._fleet_record("swap_rollback")
        committed = not abort
        if committed:
            with self._lock:
                self._prev_artifact = str(path)
                if version is not None:
                    self._committed_version = int(version)
        # consistency assertion: after a commit OR a rollback every
        # live replica must report ONE artifact identity — a fleet
        # straddling two versions is the exact bug this protocol
        # exists to prevent, so check it, loudly
        with self._lock:
            live = [r for r in self._replicas
                    if r.live and r.state != "draining"
                    and r.port is not None]
        identities = {}
        for rep in live:
            ident = self._served_identity(rep, model=model)
            if ident is not None:
                identities[rep.idx] = ident
        consistent = len(set(identities.values())) <= 1
        if not consistent:
            self._telemetry_event(
                "fleet_swap_inconsistent", path=str(path),
                identities=identities)
        with self._lock:
            self.stats["swaps"] += 1
        self._telemetry_count("fleet_swaps")
        self._telemetry_event(
            "fleet_rolling_swap", path=str(path),
            swapped=sorted(per), errors=errors,
            committed=committed, version=version)
        self._fleet_record("swap")
        if swap_ctx is not None:
            _tracing.emit_span(
                "rolling_swap", t0, time.perf_counter(), swap_ctx,
                kind="internal", committed=bool(committed),
                version=int(version) if version is not None else None,
                replicas=len(per))
        return {"per_replica": per, "errors": errors,
                "committed": committed,
                "rolled_back": sorted(rolled_back),
                "identities": identities, "consistent": consistent,
                "version": version,
                "swap_ms": round((time.perf_counter() - t0) * 1e3, 3)}

    # ------------------------------------------------------ lifecycle
    def health(self):
        with self._lock:
            reps = list(self._replicas)
            return {
                "replicas": sum(1 for r in reps if r.live),
                "ready": sum(1 for r in reps if r.state == "ready"),
                "queue_ewma": round(self.queue_ewma, 4),
                "per_replica": {
                    r.idx: {"state": r.state, "port": r.port,
                            "outstanding": r.outstanding,
                            "routed": r.routed,
                            "queue_depth": int(
                                r.last_health.get("queue_depth", 0))}
                    for r in reps},
                "stats": dict(self.stats),
            }

    def close(self, timeout=30.0):
        """Stop probing, SIGTERM every spawned replica (they drain:
        admitted work finishes, exits are rc -15), reap, and clean the
        scratch dir.  Attached endpoints are left to their owners."""
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None
        self._fleet_record("close")
        with self._lock:
            reps = [r for r in self._replicas if r.proc is not None]
        for rep in reps:
            if rep.proc.poll() is None:
                try:
                    rep.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + float(timeout)
        rcs = {}
        for rep in reps:
            left = max(0.1, deadline - time.monotonic())
            try:
                rcs[rep.idx] = rep.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rcs[rep.idx] = rep.proc.wait(timeout=10.0)
            rep.state = "dead" if rep.state != "drained" else "drained"
        self._telemetry_event("fleet_close", rcs=rcs)
        if self._dir:
            import shutil

            shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None
        return rcs

    # ------------------------------------------------------ telemetry
    def _fleet_record(self, action):
        try:
            from .. import telemetry

            rl = telemetry.current()
            if rl is None:
                return
            with self._lock:
                # snapshot under the lock, WRITE outside it: the
                # run-log flush is disk IO and the submit hot path
                # takes this lock several times per request
                reps = list(self._replicas)
                snap = {
                    "replicas": sum(1 for r in reps if r.live),
                    "ready": sum(1 for r in reps
                                 if r.state == "ready"),
                    "queue_depth": sum(
                        r.outstanding
                        + int(r.last_health.get("queue_depth", 0))
                        for r in reps if r.state == "ready"),
                    "queue_ewma": self.queue_ewma,
                    "requests": self.stats["requests"],
                    "failovers": self.stats["failovers"],
                    "shed": self.stats["shed"],
                }
            rl.fleet(action=action, **snap)
        except Exception:
            pass

    # one swallow-all telemetry shim serves the whole serving stack —
    # ModelHost reuses these too (via ModelServer); a second copy
    # would drift
    _telemetry_count = staticmethod(ModelServer._telemetry_count)
    _telemetry_event = staticmethod(ModelServer._telemetry_event)


# ================================================== the replica worker
def replica_main(argv=None):
    """Entry point of one fleet replica process
    (``python -m mxnet_tpu.serving.fleet ...``): ModelHost + HTTP
    frontend on an ephemeral port (published through ``--port-file``),
    serving until SIGTERM/SIGINT, then draining through
    ``PreemptionDrain`` — admitted work finishes, the run log closes
    with its final counters, and the exit is the clean signal death
    (rc -15) the router's scale-down/close path expects."""
    import argparse

    from ..resilience.preempt import PreemptionDrain

    ap = argparse.ArgumentParser(description="fleet replica worker")
    ap.add_argument("--artifact", action="append", required=True,
                    help="model=path of a .mxje artifact (repeat for "
                         "multi-model residency)")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--port-file", default=None)
    ap.add_argument("--hbm-budget-mb", type=float, default=None)
    ap.add_argument("--slo-ms", type=float, default=None)
    ap.add_argument("--coalesce-ms", type=float, default=1.0)
    ap.add_argument("--drain-timeout", type=float, default=30.0)
    args = ap.parse_args(argv)

    server_kw = {"coalesce_ms": args.coalesce_ms}
    if args.slo_ms is not None:
        server_kw["slo_ms"] = args.slo_ms
    host = ModelHost(hbm_budget_mb=args.hbm_budget_mb,
                     server_kw=server_kw)
    for spec in args.artifact:
        name, _, path = spec.partition("=")
        if not path:
            name, path = "model", name
        host.load(name, path)
    fe = ServeFrontend(host, port=args.port)
    fe.start()
    if args.port_file:
        # write-to-temp + rename: the router's port read can never
        # see a half-written number
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{fe.port}\n")
        os.replace(tmp, args.port_file)
    print(f"[replica] serving on {fe.port} pid={os.getpid()}",
          flush=True)
    with PreemptionDrain() as pd:
        while pd.requested is None:
            time.sleep(0.05)
        try:
            from .. import telemetry

            telemetry.event("serve_preempt", scope="fleet_replica",
                            signum=int(pd.requested))
        except Exception:
            pass
        host.drain_all(timeout=args.drain_timeout)
        fe.close()
        host.close_all()
        try:
            from .. import telemetry

            telemetry.close()  # run_end (final counters) hits disk
        except Exception:
            pass
        pd.reraise()


if __name__ == "__main__":
    replica_main()
