"""Continuous-batching model server with deadline-aware admission
control, load shedding, and crash-safe AOT warm start.

Design (Clipper/NSDI'17-style deadline-aware adaptive batching +
ORCA-style continuous batching, translated to the in-process TPU
serving shape):

* **Request queue + continuous batcher.**  ``submit()`` enqueues one
  sample; a batcher thread coalesces whatever is queued the moment the
  model frees up (plus a tiny ``coalesce_ms`` window while the batch
  is below the largest bucket), so batch size follows live queue depth
  instead of a fixed timer.  Batches are re-padded to a small set of
  **bucketed batch shapes** (powers of two up to ``max_batch`` by
  default), so the number of distinct programs the model can ever
  trace is ``len(buckets)`` — retraces are bounded by construction,
  and each new padded shape is reported as a telemetry compile event
  so the PR-5 retrace counter stays the single source of truth.

* **Deadline-aware admission control.**  Every request carries a
  deadline (explicit ``deadline_ms`` or the ``MXNET_SERVE_SLO_MS``
  SLO).  Admission estimates completion time from a running per-bucket
  latency EWMA and the queue depth, and **sheds load** — a fast
  structured :class:`ServeRejected`, never a silent hang — when the
  queue cannot meet the deadline (``reason='deadline'``), the queue is
  full (``'queue_full'``), or the breaker is open
  (``'breaker_open'``).  Deadlines propagate into the model invocation
  through :func:`mxnet_tpu.resilience.retry.retry_call`'s
  ``deadline_sec`` budget: transient model faults are retried only as
  long as the batch's tightest deadline can still be met.  At dispatch
  the deadline is re-checked — a request the EWMA says can no longer
  finish in time is shed (``'expired'``) instead of wasting a model
  slot.

* **Graceful degradation + health.**  :meth:`ModelServer.health`
  serves readiness/liveness; :meth:`ModelServer.run_until_drained`
  rides :class:`~mxnet_tpu.resilience.preempt.PreemptionDrain` so
  SIGTERM finishes admitted requests, rejects new ones
  (``'draining'``) and exits clean.  A **circuit breaker** trips after
  ``MXNET_SERVE_BREAKER_LIMIT`` consecutive model failures (exceptions
  or non-finite outputs — the serving analog of the PR-3 bad-step
  guard): while open, requests get fast rejections and the batcher
  re-warms on probe batches; a probe success closes it.

* **Crash-safe AOT warm start.**  :meth:`ModelServer.from_artifact`
  loads a ``deploy.export_model`` artifact (CRC-verified) and serves
  its ``jax.export`` program — load-not-retrace: the server emits NO
  compile events, so an armed run log's retrace counter stays 0.  The
  flight recorder (armed via ``MXNET_RUNLOG``) and the hang watchdog
  ride along, so a hard kill mid-traffic leaves a post-mortem and a
  relaunch is serving again within the warm-start budget
  (:meth:`ModelServer.warm_report`).

Telemetry: per-batch ``serve`` run-log records, Perfetto
``serve_batch`` spans on the telemetry lane, and the
``serve_requests`` / ``serve_shed`` / ``serve_batches`` /
``serve_breaker_trips`` counters (Prometheus textfile rows included).
Fault points: ``serve.admit`` (inside every admission decision),
``serve.batch`` (before each dispatched microbatch), ``serve.model``
(inside every model invocation).
"""
from __future__ import annotations

import collections
import math
import os
import threading
import time

import numpy as onp

from ..base import MXNetError
from ..resilience import faultsim
from ..resilience.retry import retry_call
from ..telemetry import tracing as _tracing

__all__ = ["ModelServer", "ServeHandle", "ServeRejected",
           "default_buckets"]

faultsim.register_point(
    "serve.admit", "serving admission decision (ModelServer.submit)")
faultsim.register_point(
    "serve.batch", "serving batcher, before each dispatched microbatch")
faultsim.register_point(
    "serve.model", "inside every serving model invocation "
                   "(delay=slow model, raise=transient failure, "
                   "nan=poisoned outputs, crash=hard death)")


def default_buckets(max_batch, step=1):
    """Power-of-two batch buckets ``(step, 2*step, ..., max_batch)`` —
    the small closed set of padded shapes that bounds retraces."""
    max_batch = int(max_batch)
    step = max(1, int(step))
    if max_batch < step or max_batch % step:
        raise MXNetError(
            f"max_batch {max_batch} not a multiple of bucket step "
            f"{step}")
    out = []
    b = step
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(sorted(set(out)))


class ServeRejected(MXNetError):
    """Structured rejection — the load-shedding contract: a request
    the server cannot serve fails FAST with a machine-readable
    ``reason``, it never hangs.

    Reasons: ``queue_full``, ``deadline`` (admission estimate misses
    the SLO), ``expired`` (dispatch-time re-check), ``breaker_open``,
    ``draining``, ``shutdown``, ``model_error``; the fleet layer
    (:mod:`.fleet`) adds ``hbm_budget`` (model residency would exceed
    the per-host HBM budget) and ``no_replica`` (every replica is
    ejected, draining or unready).
    """

    def __init__(self, reason, detail=""):
        msg = f"request rejected ({reason})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.reason = reason
        self.detail = detail


class ServeHandle:
    """Future-style handle ``submit()`` returns for an ADMITTED
    request (rejections raise :class:`ServeRejected` synchronously)."""

    __slots__ = ("_ev", "_out", "_err", "t_submit", "t_done",
                 "deadline")

    def __init__(self, deadline, t_submit):
        self._ev = threading.Event()
        self._out = None
        self._err = None
        self.t_submit = t_submit
        self.t_done = None
        self.deadline = deadline

    def _finish(self, out=None, err=None):
        if self._ev.is_set():
            return  # first terminal state wins
        self.t_done = time.monotonic()
        self._out = out
        self._err = err
        self._ev.set()

    @property
    def done(self):
        return self._ev.is_set()

    @property
    def ok(self):
        return self._ev.is_set() and self._err is None

    @property
    def latency_ms(self):
        """Submit-to-completion latency, or None while in flight."""
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e3

    def result(self, timeout=None):
        """The model output row (numpy) — or the structured error the
        request finished with.  ``timeout`` bounds the caller-side
        wait only; an un-finished request past it raises (the server
        itself never leaves admitted work unfinished)."""
        if not self._ev.wait(timeout):
            raise MXNetError(
                f"serve result not ready within {timeout}s "
                "(caller-side wait bound)")
        if self._err is not None:
            raise self._err
        return self._out


class _Request:
    __slots__ = ("x", "deadline", "t_submit", "handle", "trace",
                 "t_submit_pc")

    def __init__(self, x, deadline, t_submit, handle, trace=None,
                 t_submit_pc=None):
        self.x = x
        self.deadline = deadline
        self.t_submit = t_submit
        self.handle = handle
        # distributed-trace context captured at submit (round 20):
        # None on an untraced request — the dispatch loop emits no
        # spans for it, preserving the armed-but-untraced hot path
        self.trace = trace
        self.t_submit_pc = t_submit_pc


class ModelServer:
    """In-process continuous-batching model server (module docstring).

    Parameters
    ----------
    model_fn : callable
        ``model_fn(x_batch: np.ndarray[(b,)+item_shape]) -> array
        [(b, ...)]`` — a jitted predictor, a ``jax.export`` runner, or
        any batch-in/batch-out callable.  Must accept every bucket
        size in ``buckets``.
    item_shape : tuple
        Per-request sample shape (no batch axis).
    dtype : str
        Sample dtype requests are coerced to.
    max_batch / buckets
        The padded batch shapes: ``buckets`` wins when given, else
        ``default_buckets(max_batch)``.
    slo_ms / queue_depth / max_inflight / breaker_limit
        Override the ``MXNET_SERVE_*`` knobs (None = registry value).
    coalesce_ms : float
        How long the batcher waits for more arrivals while the batch
        is below the largest bucket (continuous batching keeps this
        tiny — the queue, not a timer, makes the batches).
    watchdog_sec : float or None
        Hang watchdog timeout for the batcher loop.  None (the
        default) follows ``MXNET_WATCHDOG_SEC`` — an operator arming
        the env knob gets the serving watchdog without touching
        code; 0 is the explicit opt-out.
    aot : bool
        True when ``model_fn`` is an ahead-of-time compiled program
        that CANNOT retrace (the ``from_artifact`` path): no compile
        events are emitted, so the run-log retrace counter staying 0
        is the load-not-retrace proof.
    """

    def __init__(self, model_fn, item_shape, dtype="float32", *,
                 max_batch=8, buckets=None, slo_ms=None,
                 queue_depth=None, max_inflight=None,
                 breaker_limit=None, coalesce_ms=2.0,
                 watchdog_sec=None, name="model", aot=False):
        from ..config import get_env

        self._model_fn = model_fn
        self.item_shape = tuple(int(s) for s in item_shape)
        self.dtype = onp.dtype(dtype)
        self.buckets = tuple(sorted({int(b) for b in buckets})) \
            if buckets else default_buckets(max_batch)
        if self.buckets[0] < 1:
            raise MXNetError(f"bad bucket sizes {self.buckets}")
        self.max_batch = self.buckets[-1]
        self.slo_ms = float(slo_ms if slo_ms is not None
                            else get_env("MXNET_SERVE_SLO_MS"))
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else get_env("MXNET_SERVE_QUEUE_DEPTH"))
        mi = int(max_inflight if max_inflight is not None
                 else get_env("MXNET_SERVE_MAX_INFLIGHT"))
        self.max_inflight = mi if mi > 0 \
            else self.queue_depth + self.max_batch
        self.breaker_limit = int(
            breaker_limit if breaker_limit is not None
            else get_env("MXNET_SERVE_BREAKER_LIMIT"))
        self.coalesce_s = max(0.0, float(coalesce_ms) / 1e3)
        self.name = str(name)
        self.aot = bool(aot)
        self._watchdog_sec = watchdog_sec

        self._cond = threading.Condition()
        self._queue = collections.deque()
        self._running = False
        self._accepting = False
        self._draining = False
        self._ready = False
        self._inflight = 0          # admitted, not yet terminal
        self._batch_running = False
        self._thread = None
        self._wd = None
        self._hb = time.monotonic()
        self._t_take_pc = None  # coalesce-start mark for trace spans
        self._ewma = {}             # bucket -> seconds
        self._ewma_alpha = 0.3
        self._breaker = "closed"
        self._consecutive_failures = 0
        self._probe_s = 0.05
        self._next_probe = 0.0
        self._traced = set()        # padded shapes already dispatched
        self._warm_start_s = None
        self.stats = {
            "requests": 0, "admitted": 0, "completed": 0, "shed": 0,
            "rejected": {}, "expired": 0, "batches": 0,
            "padded_rows": 0, "model_failures": 0, "breaker_trips": 0,
            "retraces": 0, "warm_traces": 0,
        }

    # ----------------------------------------------------- constructors
    @classmethod
    def from_artifact(cls, path, exported=None, **kw):
        """Crash-safe AOT warm start: serve a CRC-verified
        ``deploy.export_model`` artifact.  The exported program fixes
        ONE batch shape, so the bucket set is exactly that shape (all
        batches pad to it) and the server can never retrace — cold
        start is a deserialize, not a compile.  ``exported`` reuses an
        already-verified ``deploy.load_exported`` handle (the fleet's
        HBM admission sized the artifact moments ago — no second
        read)."""
        import jax.numpy as jnp

        from .. import deploy

        exp = exported if exported is not None \
            else deploy.load_exported(path)
        aval = exp.in_avals[0]
        batch = int(aval.shape[0])
        item = tuple(int(s) for s in aval.shape[1:])

        def model_fn(xb):
            return onp.asarray(exp.call(jnp.asarray(xb)))

        kw.setdefault("name", os.path.basename(str(path)))
        kw.setdefault("buckets", (batch,))
        return cls(model_fn, item, dtype=str(aval.dtype), aot=True,
                   **kw)

    @classmethod
    def from_predictor(cls, apply_fn, params, example_batch, *,
                       candidates=(1, 2, 4), tune_iters=6, **kw):
        """Serve a functionalized forward, seeded by the persisted
        ``tune_microbatch`` winners: the microbatch race runs (or
        reloads its cached winner — same process or a previous one)
        for ``example_batch``'s shape, and the server's batches run
        through the winning chunked predict program.  Buckets are the
        winner-chunk multiples up to the example batch size, so every
        padded batch divides cleanly."""
        import jax.numpy as jnp

        from ..parallel.predict import make_predict_fn, tune_microbatch

        ex = onp.asarray(example_batch)
        max_batch = int(ex.shape[0])
        (k, unroll), _ = tune_microbatch(
            apply_fn, params, jnp.asarray(ex), candidates=candidates,
            iters=tune_iters)
        predict = make_predict_fn(apply_fn, microbatch=k,
                                  unroll=unroll)

        def model_fn(xb):
            return onp.asarray(predict(params, jnp.asarray(xb)))

        kw.setdefault("buckets", default_buckets(max_batch, step=k))
        srv = cls(model_fn, tuple(ex.shape[1:]), dtype=str(ex.dtype),
                  **kw)
        srv.microbatch = (k, unroll)
        return srv

    # ---------------------------------------------------------- control
    def start(self, warm=True):
        """Start the batcher (and the hang watchdog when armed).
        ``warm=True`` runs every bucket once on dummy data BEFORE the
        server reports ready — the warm-start budget: initial latency
        EWMAs are seeded and all trace cost is paid up front, so the
        first real request never eats a compile."""
        with self._cond:
            if self._thread is not None:
                raise MXNetError(f"server {self.name!r} already "
                                 "started")
            self._running = True
        t0 = time.perf_counter()
        if warm:
            self._warmup()
        self._warm_start_s = time.perf_counter() - t0
        wd_sec = self._watchdog_sec
        if wd_sec is None:
            from ..telemetry.watchdog import default_timeout

            wd_sec = default_timeout()
        if wd_sec and wd_sec > 0:
            from ..telemetry.watchdog import Watchdog

            self._wd = Watchdog(timeout=wd_sec).arm("serve")
        self._thread = threading.Thread(
            target=self._loop, name=f"mxnet_tpu-serve-{self.name}",
            daemon=True)
        self._thread.start()
        with self._cond:
            self._accepting = True
            self._ready = True
        self._telemetry_event(
            "serve_start", model=self.name, aot=self.aot,
            buckets=list(self.buckets),
            warm_start_s=round(self._warm_start_s, 4),
            slo_ms=self.slo_ms)
        return self

    def _warmup(self):
        for b in self.buckets:
            xb = onp.zeros((b,) + self.item_shape, self.dtype)
            t0 = time.perf_counter()
            out = onp.asarray(self._model_fn(xb))
            dt = time.perf_counter() - t0
            if out.shape[0] != b:
                raise MXNetError(
                    f"model_fn returned leading axis {out.shape[0]} "
                    f"for batch {b} — serving needs batch-in/"
                    "batch-out")
            self._note_shape(xb.shape, warm=True)
            # the warmup pass includes any trace cost; a second call
            # measures the steady-state latency the EWMA should start
            # from (skipped for AOT programs — no trace to exclude)
            if not self.aot:
                t0 = time.perf_counter()
                self._model_fn(xb)
                dt = time.perf_counter() - t0
            self._ewma[b] = dt

    def drain(self, timeout=30.0):
        """Stop admitting (new submits get ``'draining'``), then wait
        until every already-admitted request reaches a terminal state.
        Returns True when fully drained inside ``timeout``."""
        with self._cond:
            self._draining = True
            self._accepting = False
            self._ready = False
            self._cond.notify_all()
        with self._cond:
            # _inflight is the race-free fence: it counts every
            # admitted-not-terminal request, including a batch the
            # batcher has POPPED but not yet marked running; _finish
            # notifies on every terminal request, so wait_for needs no
            # polling loop
            drained = self._cond.wait_for(
                lambda: self._inflight == 0, timeout=float(timeout))
        self._telemetry_event("serve_drain", model=self.name,
                              drained=drained,
                              completed=self.stats["completed"])
        return drained

    def close(self):
        """Stop the batcher.  Queued (undrained) requests fail with
        ``'shutdown'`` — terminal state always, silent hang never."""
        with self._cond:
            self._accepting = False
            self._running = False
            self._ready = False
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for r in pending:
            self._finish(r, err=ServeRejected(
                "shutdown", "server closed with the request queued"))
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        if self._wd is not None:
            self._wd.close()
            self._wd = None

    def run_until_drained(self, poll=0.05, on_drained=None):
        """Serve on the calling (main) thread until SIGTERM/SIGINT,
        then drain and exit CLEAN: in-flight admitted work finishes,
        new requests are rejected, ``on_drained(server)`` runs (flush
        results, write reports), and the signal is re-raised under its
        original disposition — the PreemptionDrain contract, serving
        edition."""
        from ..resilience.preempt import PreemptionDrain

        with PreemptionDrain() as pd:
            while pd.requested is None:
                with self._cond:
                    if not self._running:
                        break
                time.sleep(poll)
            if pd.requested is not None:
                self._telemetry_event("serve_preempt",
                                      model=self.name,
                                      signum=int(pd.requested))
            self.drain()
            self.close()
            if on_drained is not None:
                on_drained(self)
            pd.reraise()

    # -------------------------------------------------------- admission
    def submit(self, x, deadline_ms=None):
        """Admit one request (returns a :class:`ServeHandle`) or shed
        it (raises :class:`ServeRejected` — fast and structured).

        ``deadline_ms`` is relative to now; None uses the
        ``MXNET_SERVE_SLO_MS`` SLO.  Admission sheds when the queue
        bound, the in-flight bound, the open breaker, or the
        EWMA-estimated completion time says the deadline cannot be
        met."""
        faultsim.inject("serve.admit")
        now = time.monotonic()
        budget_ms = self.slo_ms if deadline_ms is None \
            else float(deadline_ms)
        deadline = now + budget_ms / 1e3
        x = onp.asarray(x, self.dtype)
        if x.shape == (1,) + self.item_shape:
            x = x[0]
        if x.shape != self.item_shape:
            raise MXNetError(
                f"request shape {x.shape} != item shape "
                f"{self.item_shape} (one sample per submit)")
        with self._cond:
            self.stats["requests"] += 1
            self._telemetry_count("serve_requests")
            if not self._accepting:
                reason = "draining" if self._draining else "shutdown"
                self._shed_locked(reason)
            if self._breaker == "open":
                self._shed_locked(
                    "breaker_open",
                    f"{self._consecutive_failures} consecutive model "
                    "failures; re-warming")
            if len(self._queue) >= self.queue_depth:
                self._shed_locked(
                    "queue_full", f"queue depth {len(self._queue)} >= "
                                  f"{self.queue_depth}")
            if self._inflight >= self.max_inflight:
                self._shed_locked(
                    "queue_full",
                    f"inflight {self._inflight} >= "
                    f"{self.max_inflight}")
            est = self._estimate_wait_locked()
            if est is not None and now + est > deadline:
                self._shed_locked(
                    "deadline",
                    f"estimated completion +{est * 1e3:.1f} ms "
                    f"exceeds deadline +{budget_ms:.1f} ms")
            h = ServeHandle(deadline, now)
            trace = t_pc = None
            if _tracing.enabled():
                trace = _tracing.current_context()
                if trace is not None:
                    t_pc = time.perf_counter()
            self._queue.append(_Request(x, deadline, now, h,
                                        trace, t_pc))
            self._inflight += 1
            self.stats["admitted"] += 1
            self._cond.notify_all()
        return h

    def _shed_locked(self, reason, detail=""):
        self.stats["shed"] += 1
        by = self.stats["rejected"]
        by[reason] = by.get(reason, 0) + 1
        self._telemetry_count("serve_shed")
        raise ServeRejected(reason, detail)

    def _estimate_wait_locked(self):
        """Seconds until a request admitted NOW would complete,
        estimated from the latency EWMA and live queue depth; None
        when no latency has been observed yet (cold server: admit —
        the first measurements teach the estimator)."""
        if not self._ewma:
            return None
        q = len(self._queue) + 1
        b = self._bucket_for(min(q, self.max_batch))
        ew = self._ewma_for_locked(b)
        batches = math.ceil(q / self.max_batch) + \
            (1 if self._batch_running else 0)
        return batches * ew

    def _ewma_for_locked(self, bucket):
        """Latency EWMA for a bucket the estimator may never have
        dispatched: an observed bucket answers directly; otherwise the
        NEAREST observed bucket's estimate is scaled by the row ratio.
        The old fallback (max over every bucket) let one slow
        large-batch probe poison small-bucket admission — a 1-request
        estimate quoted the 64-row latency and the server over-shed."""
        ew = self._ewma.get(bucket)
        if ew is not None:
            return ew
        nearest = min(self._ewma, key=lambda b: abs(b - bucket))
        return self._ewma[nearest] * (bucket / max(nearest, 1))

    def _bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch

    # ---------------------------------------------------------- batcher
    def _loop(self):
        while True:
            batch = None
            overdue = []
            detail = None
            with self._cond:
                if not self._running:
                    break
                if not self._queue:
                    if self._draining:
                        break  # drained: nothing queued, nothing new
                    self._cond.wait(0.05)
                elif self._breaker != "open":
                    batch = self._take_locked()
                elif self._draining:
                    # drain × open breaker: nothing will ever dispatch
                    # this queue — the probe re-warm is NOT waited on
                    # (it can fail forever) — so every queued request
                    # goes terminal NOW with a structured rejection
                    # and the drain completes instead of burning its
                    # whole timeout on deadlines that cannot be met
                    overdue = list(self._queue)
                    self._queue.clear()
                    detail = ("draining with the breaker open: no "
                              "dispatch can ever take this request")
                else:
                    # queued work admitted before the trip waits for
                    # the re-warm, but NEVER past its deadline: the
                    # sweep sheds overdue requests 'expired' (admitted
                    # work must not hang behind an open breaker — the
                    # dispatch-time re-check cannot run while nothing
                    # dispatches); the wait keeps the probe loop from
                    # spinning hot
                    now = time.monotonic()
                    overdue = [r for r in self._queue
                               if r.deadline <= now]
                    if overdue:
                        keep = [r for r in self._queue
                                if r.deadline > now]
                        self._queue.clear()
                        self._queue.extend(keep)
                    else:
                        self._cond.wait(0.02)
            self._shed_expired(overdue, detail=detail)
            self._hb = time.monotonic()
            if self._wd is not None:
                self._wd.beat("serve")
            if self._breaker == "open":
                if not self._draining:
                    self._try_rewarm()
                continue
            if batch:
                try:
                    self._dispatch(batch)
                except BaseException as exc:  # noqa: BLE001
                    # the batcher thread must survive anything a
                    # model/fault can throw at it: requests get a
                    # terminal error, the loop keeps serving
                    for r in batch:
                        self._finish(r, err=ServeRejected(
                            "model_error", repr(exc)))

    def _take_locked(self):
        """Coalesce: the moment the model is free we take what is
        queued, waiting at most ``coalesce_s`` for the batch to grow
        toward the largest bucket — queue depth, not a timer, sizes
        the microbatch."""
        self._t_take_pc = time.perf_counter()
        end = time.monotonic() + self.coalesce_s
        while len(self._queue) < self.max_batch and self._running:
            left = end - time.monotonic()
            if left <= 0:
                break
            self._cond.wait(left)
        k = min(len(self._queue), self.max_batch)
        return [self._queue.popleft() for _ in range(k)]

    def _dispatch(self, batch):
        now = time.monotonic()
        bucket = self._bucket_for(len(batch))
        est = self._ewma.get(bucket, 0.0)
        live, expired = [], []
        for r in batch:
            # dispatch-time re-check: the EWMA says this request can
            # no longer meet its deadline — shed it instead of burning
            # a model slot on an answer nobody will wait for
            (expired if now + est > r.deadline else live).append(r)
        self._shed_expired(expired)
        if not live:
            return
        bucket = self._bucket_for(len(live))
        with self._cond:
            self._batch_running = True
        t0 = time.perf_counter()
        try:
            # EVERYTHING that can fail a taken batch routes through
            # _model_failure — the serve.batch fault point included —
            # so shed/rejected/breaker accounting can never be skipped
            # by failing early (the _loop net is a last resort only)
            faultsim.inject("serve.batch")
            xb = onp.zeros((bucket,) + self.item_shape, self.dtype)
            for i, r in enumerate(live):
                xb[i] = r.x
            self._note_shape(xb.shape)
            # the batch's retry budget is its tightest deadline:
            # transient faults (FaultInjected) are retried only while
            # the SLA can still be met — retry.deadline_sec gives up
            # the instant it cannot, and the requests fail structured
            budget = max(0.01, min(r.deadline for r in live)
                         - time.monotonic())
            out = retry_call(
                lambda: self._invoke(xb),
                retry_on=(faultsim.FaultInjected,), attempts=3,
                base_delay=0.01, max_delay=0.2, deadline_sec=budget)
            latency = time.perf_counter() - t0
            if onp.issubdtype(out.dtype, onp.floating) \
                    and not onp.isfinite(out[:len(live)]).all():
                raise MXNetError(
                    f"non-finite model output (batch {bucket}) — the "
                    "bad-step guard's serving analog")
        except Exception as exc:  # noqa: BLE001
            self._model_failure(live, exc)
            return
        finally:
            with self._cond:
                self._batch_running = False
        self._record_success(live, bucket, latency, now, t0)
        for i, r in enumerate(live):
            self._finish(r, out=out[i])

    def _shed_expired(self, expired, detail=None):
        """Shed requests whose deadline passed while waiting —
        dispatch-time re-check, open-breaker sweep and the
        drain-with-open-breaker sweep share this one accounting path
        (under the same lock _shed_locked uses)."""
        if not expired:
            return
        with self._cond:
            self.stats["expired"] += len(expired)
            self.stats["shed"] += len(expired)
            by = self.stats["rejected"]
            by["expired"] = by.get("expired", 0) + len(expired)
        for r in expired:
            self._telemetry_count("serve_shed")
            self._finish(r, err=ServeRejected(
                "expired",
                detail or "deadline passed before the model could "
                          "take the request"))

    def _invoke(self, xb):
        poison = faultsim.inject("serve.model")
        out = onp.asarray(self._model_fn(xb))
        if poison == "nan" and onp.issubdtype(out.dtype,
                                              onp.floating):
            out = onp.full_like(out, onp.nan)
        return out

    def _note_shape(self, shape, warm=False):
        """Bounded-retrace accounting: the first dispatch of a padded
        shape is (at most) one new model program.  Reported as a
        telemetry compile event — EXCEPT for AOT programs, which
        cannot retrace; their run log keeps compiles == 0, the
        load-not-retrace proof."""
        if shape in self._traced:
            return
        self._traced.add(shape)
        self.stats["warm_traces" if warm else "retraces"] += 1
        if self.aot:
            return
        from .. import telemetry

        telemetry.compile_event(
            f"serve:{self.name}",
            telemetry.compile_fingerprint(shape, self.dtype,
                                          train=False))

    def _record_success(self, live, bucket, latency, t_dispatch,
                        t_invoke=None):
        with self._cond:
            prev = self._ewma.get(bucket)
            self._ewma[bucket] = latency if prev is None else \
                (1 - self._ewma_alpha) * prev + \
                self._ewma_alpha * latency
            self._consecutive_failures = 0
            self.stats["batches"] += 1
            self.stats["padded_rows"] += bucket - len(live)
            qd = len(self._queue)
            shed = self.stats["shed"]
        self._telemetry_count("serve_batches")
        margin_ms = min(
            (r.deadline - time.monotonic()) * 1e3 for r in live)
        from .. import telemetry

        rl = telemetry.current()
        if rl is not None:
            if t_invoke is not None:
                self._emit_request_spans(rl, live, bucket, t_invoke,
                                         t_invoke + latency)
            rl.serve(model=self.name, batch=len(live),
                     padded_to=bucket, queue_depth=qd,
                     latency_ms=latency * 1e3,
                     deadline_margin_ms=margin_ms, shed=shed,
                     breaker=self._breaker)

    def _emit_request_spans(self, rl, live, bucket, t_invoke, t_end):
        """Per-request TTL decomposition for TRACED requests (round
        20): ``serve_queue`` (submit -> batch taken), ``serve_coalesce``
        (batch formation -> model invoke) and ``serve_model`` (the
        invocation), all siblings under the request's captured context.
        Untraced requests cost one attribute check; the spans queue
        unflushed behind the batch's flushing ``serve`` record."""
        t_take = self._t_take_pc
        for r in live:
            ctx = r.trace
            if ctx is None:
                continue
            qs = r.t_submit_pc
            cs = min(max(qs, t_take if t_take is not None
                         else t_invoke), t_invoke)
            for name, a, b in (("serve_queue", qs, cs),
                               ("serve_coalesce", cs, t_invoke),
                               ("serve_model", t_invoke, t_end)):
                rl.span(name, a, b, trace_id=ctx.trace_id,
                        span_id=_tracing.new_span_id(),
                        parent_span_id=ctx.span_id, flush=False,
                        model=self.name, padded_to=int(bucket))

    def _model_failure(self, live, exc):
        err = exc if isinstance(exc, ServeRejected) else ServeRejected(
            "model_error", repr(exc))
        trip = False
        with self._cond:
            self.stats["model_failures"] += 1
            self._consecutive_failures += 1
            # the batch's requests end as structured rejections: they
            # count in shed and in the by-reason breakdown like every
            # other rejection, so shed == sum(rejected.values()) holds
            self.stats["shed"] += len(live)
            by = self.stats["rejected"]
            by[err.reason] = by.get(err.reason, 0) + len(live)
            if self._breaker == "closed" and \
                    self._consecutive_failures >= self.breaker_limit:
                self._breaker = "open"
                self.stats["breaker_trips"] += 1
                self._probe_s = 0.05
                self._next_probe = time.monotonic() + self._probe_s
                trip = True
        self._telemetry_count("serve_shed", len(live))
        for r in live:
            self._finish(r, err=err)
        self._telemetry_event("serve_model_failure", model=self.name,
                              error=repr(exc),
                              consecutive=self._consecutive_failures)
        if trip:
            self._telemetry_count("serve_breaker_trips")
            self._telemetry_event(
                "serve_breaker", model=self.name, state="open",
                failures=self._consecutive_failures)

    def _try_rewarm(self):
        """Breaker open: serve rejections while probing — one dummy
        smallest-bucket batch per (backing-off) probe interval; a
        finite probe result closes the breaker and serving resumes."""
        if time.monotonic() < self._next_probe:
            return
        xb = onp.zeros((self.buckets[0],) + self.item_shape,
                       self.dtype)
        try:
            out = self._invoke(xb)
            if onp.issubdtype(out.dtype, onp.floating) \
                    and not onp.isfinite(out).all():
                raise MXNetError("non-finite probe output")
        except Exception:  # noqa: BLE001 — still broken: back off
            self._probe_s = min(self._probe_s * 2.0, 2.0)
            self._next_probe = time.monotonic() + self._probe_s
            return
        # a warm=False server's probe can be the FIRST dispatch of the
        # smallest bucket: account the trace like any other dispatch
        self._note_shape((self.buckets[0],) + self.item_shape)
        with self._cond:
            self._breaker = "closed"
            self._consecutive_failures = 0
        self._telemetry_event("serve_breaker", model=self.name,
                              state="closed")

    def _finish(self, req, out=None, err=None):
        if req.handle.done:
            return  # already terminal: the inflight count must not
            #         double-decrement (loop safety net vs dispatch)
        req.handle._finish(out=out, err=err)
        with self._cond:
            self._inflight -= 1
            if err is None:
                self.stats["completed"] += 1
            self._cond.notify_all()

    # ----------------------------------------------------------- health
    def health(self):
        """Readiness/liveness probe payload.  ``live``: the batcher
        thread exists and made progress recently (or is legitimately
        inside a model call).  ``ready``: started, warm, admitting,
        breaker closed — safe to route traffic to."""
        with self._cond:
            alive = self._thread is not None \
                and self._thread.is_alive()
            hb_age = time.monotonic() - self._hb
            ew = max(self._ewma.values()) if self._ewma else 0.0
            # the coalesce window is legitimate quiet time: the
            # batcher beats only after _take_locked returns, so the
            # bound must absorb it or a long-coalesce healthy server
            # reads as dead to the probe
            quiet_bound = max(1.0, 10.0 * ew) + self.coalesce_s
            live = alive and (self._batch_running
                              or hb_age < quiet_bound)
            payload = {
                "live": bool(live),
                "ready": bool(self._ready and self._accepting
                              and alive
                              and self._breaker == "closed"),
                "breaker": self._breaker,
                "draining": self._draining,
                "queue_depth": len(self._queue),
                "inflight": self._inflight,
                "heartbeat_age_s": round(hb_age, 3),
                "buckets": list(self.buckets),
                "ewma_ms": {b: round(v * 1e3, 3)
                            for b, v in sorted(self._ewma.items())},
            }
        # readiness/liveness as Prometheus gauges (outside the lock):
        # the fleet's health probes and an external textfile scraper
        # read the SAME truth this method just computed.  The rows
        # are labeled per model so two servers in one process cannot
        # overwrite each other's readiness (and a 1/0 interleave
        # cannot re-trigger the change-detecting textfile rewrite on
        # every probe); a multi-model host suppresses these and
        # publishes its unlabeled aggregate instead
        if not getattr(self, "_suppress_health_gauges", False):
            label = f'{{model="{self.name}"}}'
            self._telemetry_gauge(f"serve_ready{label}",
                                  int(payload["ready"]))
            self._telemetry_gauge(f"serve_live{label}",
                                  int(payload["live"]))
        return payload

    def live(self):
        return self.health()["live"]

    def ready(self):
        return self.health()["ready"]

    def warm_report(self):
        """The warm-start contract: how long start() took, whether the
        program was AOT (load-not-retrace), and how many NEW padded
        shapes were dispatched after warmup (steady-state retraces —
        0 once every bucket is warm)."""
        return {"warm_start_s": self._warm_start_s, "aot": self.aot,
                "buckets": list(self.buckets),
                "warm_traces": self.stats["warm_traces"],
                "steady_state_traces": self.stats["retraces"]}

    # -------------------------------------------------------- telemetry
    @staticmethod
    def _telemetry_count(counter, delta=1):
        try:
            from .. import telemetry

            telemetry.count(counter, delta)
        except Exception:
            pass

    @staticmethod
    def _telemetry_event(kind, **fields):
        try:
            from .. import telemetry

            telemetry.event(kind, **fields)
        except Exception:
            pass

    @staticmethod
    def _telemetry_gauge(name, value):
        try:
            from .. import telemetry

            telemetry.gauge(name, value)
        except Exception:
            pass
