"""Generative decode serving: paged-KV continuous batching (round 17).

The ModelServer/fleet stack (rounds 8/10/13) batches STATELESS
single-shot requests; this module serves the workload it cannot — an
autoregressive transformer where every sequence carries per-request
device state (the KV cache) across many steps.  Two canonical levers,
built from parts the repo already has:

**Paged KV cache** (serving.kvcache.PagedKVPool): per-sequence KV
blocks allocated from a fixed physical page pool sized under an HBM
byte budget (the ModelHost admission idea applied to decode state).
Admission is by TOKEN budget — a sequence reserves pages for
``prompt + max_new`` up front, so an admitted sequence can never OOM
the pool mid-decode.  ``MXNET_KV_DTYPE=int8`` stores pages int8 with
per-(token, head) scales (quantization.kv) — ~2.7x the concurrent
sequences at head_dim 8 — gated by a warmup output-agreement probe
against an fp32-cache arm, exactly like the round-13 int8 adoption
floor.

**Prefill/decode disaggregation** with token-level continuous
batching (the ORCA schedule round 8's batcher cites): prompts prefill
one at a time on BUCKETED lengths (compile events bounded by the
bucket list and counted like ModelServer._note_shape), racing
``flash_attention``'s pallas_pad variant on the ragged shapes; the
decode loop then runs over a FIXED-capacity slot tensor
(``MXNET_DECODE_SLOTS``) so the decode step compiles ONCE — sequences
are admitted/evicted by in-place slot updates (page-table rows,
seq_lens, last-token ids), never by retrace.  Decode attention walks
the page table via ops.flash_attention.paged_decode_attention, whose
gather/paged variants race through autotune like every other kernel.

Failure story mirrors ModelServer: a ``serve.decode`` faultsim point
fires inside every decode step; consecutive failures trip the breaker
— in-flight sequences finish with structured
``ServeRejected(reason="model_error")``, queued requests shed
``breaker_open``, and EVERY pool page is reclaimed (the no-page-leak
invariant the chaos campaign asserts) — then probe steps re-warm and
close it.

Telemetry: ``generate`` run-log records (tokens/s, TTFT p50/p99,
sequences-in-flight, eviction/shed counts), counters
``serve_tokens_total`` / ``kv_evictions_total`` and gauges
``kv_pages_in_use`` / ``prefill_queue_depth`` — all in the Prometheus
textfile.
"""
from __future__ import annotations

import collections
import functools
import math
import threading
import time

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..quantization.kv import kv_quantize
from ..resilience import faultsim
from ..telemetry import tracing as _tracing
from .kvcache import PagedKVPool
from .server import ServeRejected

__all__ = ["GenerativeServer", "GenerateHandle", "toy_decoder_params"]

faultsim.register_point(
    "serve.decode",
    "inside every generative decode step (delay=slow token, "
    "raise=transient step failure, nan=poisoned logits, crash=hard "
    "death)")
faultsim.register_point(
    "serve.prefill", "before each bucketed prefill dispatch")


def toy_decoder_params(seed=0, vocab=32, layers=2, heads=2, head_dim=8,
                       mlp_mult=2):
    """Deterministic decoder-only transformer params (pre-norm rmsnorm
    blocks, tied nothing) — the synthetic generative model the bench
    phase and tests drive.  The attention output projection is scaled
    DOWN so greedy argmax margins stay wide relative to int8
    KV-cache noise while the cache path remains load-bearing (zeroing
    it flips ~1/3 of generated tokens); agreement is still measured,
    never assumed."""
    embed = heads * head_dim
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 2 + 6 * layers)

    def init(k, shape, scale):
        return jax.random.normal(k, shape, jnp.float32) * scale

    params = {
        "embed": init(ks[0], (vocab, embed), 1.0),
        "head": init(ks[1], (embed, vocab), 3.0 / embed ** 0.5),
        "lnf": jnp.ones((embed,), jnp.float32),
        "layers": [],
    }
    i = 2
    for _ in range(layers):
        params["layers"].append({
            "wq": init(ks[i + 0], (embed, embed), embed ** -0.5),
            "wk": init(ks[i + 1], (embed, embed), embed ** -0.5),
            "wv": init(ks[i + 2], (embed, embed), embed ** -0.5),
            "wo": init(ks[i + 3], (embed, embed), 0.25 * embed ** -0.5),
            "w1": init(ks[i + 4], (embed, mlp_mult * embed),
                       embed ** -0.5),
            "w2": init(ks[i + 5], (mlp_mult * embed, embed),
                       (mlp_mult * embed) ** -0.5),
            "ln1": jnp.ones((embed,), jnp.float32),
            "ln2": jnp.ones((embed,), jnp.float32),
        })
        i += 6
    return params


def _rmsnorm(x, g):
    return x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True)
                             + 1e-6) * g


class GenerateHandle:
    """Future for one generation request (ServeHandle's generative
    sibling): resolves to the generated token list or raises the
    structured ServeRejected the scheduler assigned."""

    def __init__(self, seq_id):
        self.seq_id = seq_id
        self._done = threading.Event()
        self._tokens = None
        self._err = None
        self.ttft_ms = None
        self.latency_ms = None
        self.evicted = 0

    def _finish(self, tokens=None, err=None):
        if self._done.is_set():
            return
        self._tokens = tokens
        self._err = err
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"generation {self.seq_id} still running")
        if self._err is not None:
            raise self._err
        return self._tokens


class _Seq:
    __slots__ = ("id", "handle", "prompt", "max_new", "generated",
                 "slot", "t_submit", "t_first", "deadline", "evictions",
                 "counted_admit", "trace", "t_submit_pc", "t_first_pc")

    def __init__(self, seq_id, handle, prompt, max_new, deadline,
                 trace=None):
        self.id = seq_id
        self.handle = handle
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.generated = []
        self.slot = None
        self.t_submit = time.monotonic()
        self.t_first = None
        self.deadline = deadline
        self.evictions = 0
        self.counted_admit = False
        # round-20 trace context captured at submit (None = untraced:
        # the scheduler emits no spans for this sequence)
        self.trace = trace
        self.t_submit_pc = time.perf_counter() if trace is not None \
            else None
        self.t_first_pc = None

    @property
    def context(self):
        """Tokens to (re)prefill: the prompt plus everything already
        generated — an evicted sequence resumes EXACTLY where the
        preemption cut it."""
        return self.prompt + self.generated

    @property
    def budget_tokens(self):
        """Pages are reserved for this many tokens at admission — the
        token-budget admission unit."""
        return len(self.prompt) + self.max_new


class GenerativeServer:
    """Token-level continuous-batching server over a paged KV cache.

    ``submit(prompt_tokens, max_new=...)`` returns a
    :class:`GenerateHandle`; a scheduler thread prefills queued
    prompts into free decode slots (token-budget admission against
    the page pool) and steps ALL active slots one token at a time
    through the compile-once decode program, admitting and evicting
    between tokens.
    """

    def __init__(self, params=None, seed=0, vocab=32, layers=2, heads=2,
                 head_dim=8, prompt_buckets=(4, 8, 16), max_new=16,
                 slots=None, page_tokens=None, pool_budget=None,
                 kv_dtype=None, agreement_floor=0.99, slo_ms=5000.0,
                 queue_depth=64, breaker_limit=3, evict_after_ms=100.0,
                 eos_id=None, name="generate", kv_gate=True):
        from ..config import get_env

        self.name = name
        self.vocab = int(vocab)
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.params = params if params is not None else \
            toy_decoder_params(seed=seed, vocab=vocab, layers=layers,
                               heads=heads, head_dim=head_dim)
        self.prompt_buckets = tuple(sorted(set(int(b)
                                               for b in prompt_buckets)))
        self.max_new = int(max_new)
        self.slots = int(slots if slots is not None
                         else get_env("MXNET_DECODE_SLOTS"))
        self.slo_ms = float(slo_ms)
        self.queue_depth = int(queue_depth)
        self.breaker_limit = int(breaker_limit)
        self.evict_after_ms = float(evict_after_ms)
        self.eos_id = eos_id
        self.agreement_floor = float(agreement_floor)
        self._kv_gate = bool(kv_gate)
        self._page_tokens = page_tokens
        self._pool_budget = pool_budget
        self._kv_dtype_requested = str(
            kv_dtype if kv_dtype is not None
            else get_env("MXNET_KV_DTYPE"))
        self.kv_agreement = None

        self.max_seq_tokens = self.prompt_buckets[-1] + self.max_new
        self.pool = None
        self.stats = {
            "requests": 0, "admitted": 0, "completed": 0, "shed": 0,
            "rejected": {}, "tokens": 0, "prefills": 0, "evictions": 0,
            "decode_failures": 0, "breaker_trips": 0, "compiles": 0,
            "warm_traces": 0, "max_in_flight": 0,
            "kv_dtype_effective": None,
        }
        self._ttft_ms = []
        self._latency_ms = []
        self._lock = threading.RLock()
        self._queue = collections.deque()
        self._seq_counter = 0
        self._stop = False
        self._draining = False
        self._started = False
        self._breaker_open = False
        self._fail_count = 0
        self._rewarm_at = 0.0
        self._rewarm_backoff = 0.05
        self._thread = None
        self._traced = set()
        self._t_start = time.monotonic()
        self._prefill_jits = {}
        self._prefill_variants = {}
        self._paged_variant = None
        self._decode_jit = None
        self._autotune_report = {}

    # ------------------------------------------------------- lifecycle
    def start(self, warm=True):
        if self._started:
            return self
        self._build(self._kv_dtype_requested, warm=warm)
        self._thread = threading.Thread(target=self._loop,
                                        name=f"{self.name}-sched",
                                        daemon=True)
        self._started = True
        self._thread.start()
        if warm and self.pool.dtype == "int8" and self._kv_gate:
            agreement = self._agreement_probe()
            self.kv_agreement = agreement
            if agreement < self.agreement_floor:
                # the round-13 adoption contract: below the measured
                # floor, int8 never ships — rebuild the pool fp32
                self._build("float32", warm=warm)
        self.stats["kv_dtype_effective"] = self.pool.dtype
        self._reset_campaign_stats()
        return self

    def _build(self, kv_dtype, warm):
        with self._lock:
            if self.pool is not None:
                self.pool.reset()
            self.pool = PagedKVPool(
                self.layers, self.heads, self.head_dim,
                page_tokens=self._page_tokens,
                budget_bytes=self._pool_budget, dtype=kv_dtype)
            self.max_pages = self.pool.pages_needed(self.max_seq_tokens)
            s = self.slots
            self._slot_seq = [None] * s
            self._page_table = onp.zeros((s, self.max_pages), onp.int32)
            self._seq_lens = onp.zeros(s, onp.int32)
            self._last_tokens = onp.zeros(s, onp.int32)
            self._active = onp.zeros(s, bool)
            self._prefill_jits = {}
            self._decode_jit = None
            self._race_variants()
            if warm:
                self._warmup()

    def _race_variants(self):
        """Warmup-time autotune races: flash_attention's pallas_pad
        shim on each ragged prefill bucket shape, and the paged decode
        attention's gather-vs-paged walk on the real pool shape.
        Cached winners answer without re-measuring (tune's level-1
        contract); winners bind STATICALLY into the jitted programs."""
        from .. import autotune

        report = {}
        for bucket in self.prompt_buckets:
            shape = (1, self.heads, bucket, self.head_dim)
            winner, info = autotune.tune(
                "flash_attention", shape, "float32",
                {k: v for k, v in
                 autotune.VARIANT_OPS["flash_attention"].items()
                 if k in ("naive", "pallas_pad")},
                functools.partial(self._measure_prefill, bucket))
            self._prefill_variants[bucket] = winner
            report[f"prefill_b{bucket}"] = {"winner": winner, **info}
        pool_shape = (self.slots, self.pool.num_pages + 1,
                      self.pool.page_tokens, self.heads, self.head_dim)
        winner, info = autotune.tune(
            "paged_decode_attention", pool_shape, self.pool.dtype,
            autotune.VARIANT_OPS["paged_decode_attention"],
            self._measure_paged)
        self._paged_variant = winner
        report["paged_decode_attention"] = {"winner": winner, **info}
        self._autotune_report = report

    def _measure_prefill(self, bucket, _value):
        from ..autotune import chain_time

        toks = jnp.zeros((1, bucket), jnp.int32)

        def body(carry, i):
            logits, _, _ = self._prefill_fn(self.params,
                                            toks + carry.astype(jnp.int32)
                                            % self.vocab)
            return logits[0, -1, 0]

        return chain_time(body, jnp.float32(0.0), iters=4)

    def _measure_paged(self, _value):
        from ..autotune import chain_time
        from ..ops.flash_attention import paged_decode_attention

        k_pages, v_pages, k_scale, v_scale = self.pool.arrays()
        int8 = self.pool.dtype == "int8"
        q = jnp.ones((self.slots, self.heads, self.head_dim),
                     jnp.float32)
        pt = jnp.zeros((self.slots, self.max_pages), jnp.int32)
        sl = jnp.full((self.slots,), self.pool.page_tokens, jnp.int32)

        def body(carry, i):
            out = paged_decode_attention(
                q + carry, k_pages[0], v_pages[0], pt, sl,
                k_scale=k_scale[0] if int8 else None,
                v_scale=v_scale[0] if int8 else None)
            return out[0, 0, 0]

        return chain_time(body, jnp.float32(0.0), iters=4)

    def _warmup(self):
        """Compile every program the campaign will need: one prefill
        per bucket, the decode step, and the write paths — so a bursty
        campaign with admits/evictions shows ZERO new compile events
        (stats['compiles'] stays 0, the continuous-batching proof)."""
        for bucket in self.prompt_buckets:
            toks = jnp.zeros((1, bucket), jnp.int32)
            logits, k, v = self._prefill(bucket)(self.params, toks)
            self._note_program(("prefill", bucket), warm=True)
            jax.block_until_ready(logits)
        # decode over the all-inactive slot state compiles the ONE
        # decode program; write paths compile via a scratch pool write
        self._decode_state_step()
        self._note_program(("decode", self.slots), warm=True)
        scratch = "__warm__"
        self.pool.alloc(scratch, self.pool.page_tokens)
        zeros = jnp.zeros((self.layers, 1, self.heads, self.head_dim),
                          jnp.float32)
        self.pool.write_prompt(scratch, zeros, zeros)
        self.pool.free(scratch)

    def _reset_campaign_stats(self):
        with self._lock:
            keep_warm = self.stats["warm_traces"]
            keep_dtype = self.stats["kv_dtype_effective"]
            for k in ("requests", "admitted", "completed", "shed",
                      "tokens", "prefills", "evictions",
                      "decode_failures", "breaker_trips", "compiles",
                      "max_in_flight"):
                self.stats[k] = 0
            self.stats["rejected"] = {}
            self.stats["warm_traces"] = keep_warm
            self.stats["kv_dtype_effective"] = keep_dtype
            self._ttft_ms = []
            self._latency_ms = []
            self._t_start = time.monotonic()

    def _agreement_probe(self, n_prompts=4, max_new=8):
        """Per-token greedy agreement of THIS (int8-cache) server
        against a throwaway fp32-cache sibling on deterministic probe
        prompts — the measured gate deciding whether int8 ships."""
        prompts = [[(3 * i + j) % self.vocab
                    for j in range(2 + i % (self.prompt_buckets[0]))]
                   for i in range(n_prompts)]
        ref = GenerativeServer(
            params=self.params, vocab=self.vocab, layers=self.layers,
            heads=self.heads, head_dim=self.head_dim,
            prompt_buckets=self.prompt_buckets, max_new=max_new,
            slots=self.slots, page_tokens=self.pool.page_tokens,
            pool_budget=self._pool_budget
            if self._pool_budget is not None else None,
            kv_dtype="float32", kv_gate=False, name=f"{self.name}-ref")
        ref.start(warm=False)
        try:
            mine = [self.submit(p, max_new=max_new,
                                deadline_ms=60000).result(timeout=60)
                    for p in prompts]
            theirs = [ref.submit(p, max_new=max_new,
                                 deadline_ms=60000).result(timeout=60)
                      for p in prompts]
        finally:
            ref.close()
        agree = total = 0
        for a, b in zip(mine, theirs):
            for x, y in zip(a, b):
                agree += int(x == y)
                total += 1
        return agree / max(total, 1)

    # ------------------------------------------------------- the model
    def _prefill_fn(self, params, tokens, variant=None):
        from ..ops.flash_attention import flash_attention

        b, seq = tokens.shape
        heads, d = self.heads, self.head_dim
        x = params["embed"][tokens]
        k_all, v_all = [], []
        for lyr in params["layers"]:
            h = _rmsnorm(x, lyr["ln1"])
            q = (h @ lyr["wq"]).reshape(b, seq, heads, d)
            k = (h @ lyr["wk"]).reshape(b, seq, heads, d)
            v = (h @ lyr["wv"]).reshape(b, seq, heads, d)
            attn = flash_attention(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), causal=True, variant=variant)
            x = x + attn.transpose(0, 2, 1, 3).reshape(b, seq,
                                                       heads * d) \
                @ lyr["wo"]
            h2 = _rmsnorm(x, lyr["ln2"])
            x = x + jax.nn.gelu(h2 @ lyr["w1"]) @ lyr["w2"]
            k_all.append(k)
            v_all.append(v)
        x = _rmsnorm(x, params["lnf"])
        logits = x @ params["head"]
        return logits, jnp.stack(k_all), jnp.stack(v_all)

    def _prefill(self, bucket):
        jit = self._prefill_jits.get(bucket)
        if jit is None:
            jit = jax.jit(functools.partial(
                self._prefill_fn,
                variant=self._prefill_variants.get(bucket)))
            self._prefill_jits[bucket] = jit
        return jit

    def _decode_fn(self, params, k_pages, v_pages, k_scale, v_scale,
                   page_table, seq_lens, last_tokens, active,
                   variant=None):
        from ..ops.flash_attention import paged_decode_attention

        s = last_tokens.shape[0]
        heads, d = self.heads, self.head_dim
        t = self.pool.page_tokens
        int8 = self.pool.dtype == "int8"
        x = params["embed"][last_tokens]
        page_idx = page_table[jnp.arange(s), seq_lens // t]
        offset = seq_lens % t
        # the just-written token is attended in the same step; an
        # inactive slot masks everything out (exact-zero output row)
        eff_len = jnp.where(active, seq_lens + 1, 0)
        for li, lyr in enumerate(params["layers"]):
            h = _rmsnorm(x, lyr["ln1"])
            q = (h @ lyr["wq"]).reshape(s, heads, d)
            k_new = (h @ lyr["wk"]).reshape(s, heads, d)
            v_new = (h @ lyr["wv"]).reshape(s, heads, d)
            if int8:
                kq, ksc = kv_quantize(k_new)
                vq, vsc = kv_quantize(v_new)
                k_pages = k_pages.at[li, page_idx, offset].set(kq)
                v_pages = v_pages.at[li, page_idx, offset].set(vq)
                k_scale = k_scale.at[li, page_idx, offset].set(ksc)
                v_scale = v_scale.at[li, page_idx, offset].set(vsc)
                attn = paged_decode_attention(
                    q, k_pages[li], v_pages[li], page_table, eff_len,
                    k_scale=k_scale[li], v_scale=v_scale[li],
                    variant=variant)
            else:
                k_pages = k_pages.at[li, page_idx, offset].set(
                    k_new.astype(k_pages.dtype))
                v_pages = v_pages.at[li, page_idx, offset].set(
                    v_new.astype(v_pages.dtype))
                attn = paged_decode_attention(
                    q, k_pages[li], v_pages[li], page_table, eff_len,
                    variant=variant)
            x = x + attn.reshape(s, heads * d) @ lyr["wo"]
            h2 = _rmsnorm(x, lyr["ln2"])
            x = x + jax.nn.gelu(h2 @ lyr["w1"]) @ lyr["w2"]
        x = _rmsnorm(x, params["lnf"])
        logits = x @ params["head"]
        next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        next_tok = jnp.where(active, next_tok, last_tokens)
        seq_lens = jnp.where(active, seq_lens + 1, seq_lens)
        return k_pages, v_pages, k_scale, v_scale, seq_lens, next_tok

    def _decode(self):
        if self._decode_jit is None:
            self._decode_jit = jax.jit(
                functools.partial(self._decode_fn,
                                  variant=self._paged_variant),
                donate_argnums=(1, 2, 3, 4))
        return self._decode_jit

    def decode_cache_size(self):
        """Compiled-program count of the decode step (None when jax
        hides it) — the direct compile-once proof tests assert on."""
        jit = self._decode_jit
        size = getattr(jit, "_cache_size", None)
        return size() if callable(size) else None

    def _decode_state_step(self):
        """One decode step over the CURRENT slot state; updates the
        pool arrays and host mirrors, returns the per-slot next-token
        row.  Raises on injected faults (the caller owns breaker
        accounting)."""
        poison = faultsim.inject("serve.decode")
        if poison == "nan":
            raise MXNetError(
                "non-finite decode logits (poisoned by fault "
                "injection)")
        k_pages, v_pages, k_scale, v_scale = self.pool.arrays()
        out = self._decode()(
            self.params, k_pages, v_pages, k_scale, v_scale,
            jnp.asarray(self._page_table), jnp.asarray(self._seq_lens),
            jnp.asarray(self._last_tokens), jnp.asarray(self._active))
        k_pages, v_pages, k_scale, v_scale, seq_lens, next_tok = out
        self.pool.set_arrays(k_pages, v_pages, k_scale, v_scale)
        self._seq_lens = onp.asarray(seq_lens).copy()
        next_np = onp.asarray(next_tok).copy()
        self._last_tokens = next_np
        return next_np

    # ------------------------------------------------------ accounting
    def _note_program(self, key, warm=False):
        if key in self._traced:
            return
        self._traced.add(key)
        with self._lock:
            self.stats["warm_traces" if warm else "compiles"] += 1
        try:
            from .. import telemetry

            kind, size = key
            telemetry.compile_event(
                f"generate:{self.name}:{kind}",
                telemetry.compile_fingerprint((size,), "float32",
                                              train=False))
        except Exception:
            pass

    @staticmethod
    def _telemetry_count(name, n=1):
        try:
            from .. import telemetry

            telemetry.count(name, n)
        except Exception:
            pass

    @staticmethod
    def _telemetry_gauge(name, value):
        try:
            from .. import telemetry

            telemetry.gauge(name, value)
        except Exception:
            pass

    def _reject(self, reason, detail=""):
        with self._lock:
            self.stats["shed"] += 1
            self.stats["rejected"][reason] = \
                self.stats["rejected"].get(reason, 0) + 1
        return ServeRejected(reason, detail)

    # ------------------------------------------------------- admission
    def submit(self, prompt, max_new=None, deadline_ms=None):
        """Queue a prompt (iterable of token ids) for generation.

        Token-budget admission: the request is rejected outright
        (``reason="token_budget"``) when ``prompt + max_new`` exceeds
        what the whole pool could EVER hold, and queues otherwise —
        the scheduler admits it into a decode slot once pages AND a
        slot are free, evicting under pressure."""
        faultsim.inject("serve.admit")
        prompt = [int(x) for x in prompt]
        max_new = self.max_new if max_new is None else int(max_new)
        budget_ms = self.slo_ms if deadline_ms is None \
            else float(deadline_ms)
        with self._lock:
            if not self._started or self._stop:
                raise self._reject("shutdown", "server not running")
            if self._draining:
                raise self._reject("draining", "server is draining")
            if self._breaker_open:
                raise self._reject(
                    "breaker_open",
                    "circuit breaker open after consecutive decode "
                    "failures")
            if len(self._queue) >= self.queue_depth:
                raise self._reject(
                    "queue_full", f"{len(self._queue)} queued")
            if not prompt or len(prompt) > self.prompt_buckets[-1]:
                raise self._reject(
                    "token_budget",
                    f"prompt length {len(prompt)} outside (0, "
                    f"{self.prompt_buckets[-1]}]")
            total = len(prompt) + max_new
            if self.pool.pages_needed(total) > self.pool.num_pages:
                raise self._reject(
                    "token_budget",
                    f"{total} tokens exceed the pool's "
                    f"{self.pool.capacity_tokens}-token budget")
            self._seq_counter += 1
            handle = GenerateHandle(self._seq_counter)
            trace = None
            if _tracing.enabled():
                cur = _tracing.current_context()
                # entry point: adopt the caller's context, else root a
                # fresh trace for this generation request
                trace = cur.child() if cur is not None \
                    else _tracing.mint()
            seq = _Seq(self._seq_counter, handle, prompt, max_new,
                       time.monotonic() + budget_ms / 1e3, trace=trace)
            self._queue.append(seq)
            self.stats["requests"] += 1
            self._telemetry_gauge("prefill_queue_depth",
                                  len(self._queue))
        return handle

    def _bucket_for(self, n):
        for b in self.prompt_buckets:
            if n <= b:
                return b
        raise MXNetError(f"no prefill bucket holds {n} tokens")

    def _free_slot(self):
        for i, s in enumerate(self._slot_seq):
            if s is None:
                return i
        return None

    def _admit(self):
        """Admit queued sequences into free slots — between TOKENS,
        the continuous-batching schedule.  Under page/slot pressure
        the head may preempt (evict) the most recently admitted
        sequence after ``evict_after_ms``; an evicted sequence resumes
        via re-prefill of prompt+generated and is never evicted
        twice."""
        while True:
            with self._lock:
                if not self._queue or self._stop or self._breaker_open:
                    return
                seq = self._queue[0]
                now = time.monotonic()
                if now > seq.deadline:
                    self._queue.popleft()
                    seq.handle._finish(err=self._reject(
                        "expired", "deadline passed while queued"))
                    self._telemetry_gauge("prefill_queue_depth",
                                          len(self._queue))
                    continue
                slot = self._free_slot()
                ok = slot is not None and \
                    self.pool.can_admit(seq.budget_tokens)
                if ok:
                    self._queue.popleft()
                    self._telemetry_gauge("prefill_queue_depth",
                                          len(self._queue))
                else:
                    waited_ms = (now - seq.t_submit) * 1e3
                    if waited_ms >= self.evict_after_ms:
                        victim = self._evict_candidate()
                        if victim is not None:
                            self._evict(victim)
                            continue
                    return
            if ok:
                try:
                    self._install(seq, slot)
                except ServeRejected as err:
                    seq.handle._finish(err=err)
                except Exception as exc:
                    self._model_failure(exc)
                    seq.handle._finish(err=self._reject(
                        "model_error", repr(exc)))
                    return

    def _evict_candidate(self):
        """The most recently admitted active sequence that has never
        been evicted (caller holds the lock); None = nobody evictable,
        the head keeps waiting.  A victim must also still FIT a
        prefill bucket on resume — once prompt+generated outgrows the
        largest bucket the sequence can only finish in place."""
        best = None
        for seq in self._slot_seq:
            if seq is None or seq.evictions > 0:
                continue
            if len(seq.context) > self.prompt_buckets[-1]:
                continue
            if best is None or seq.id > best.id:
                best = seq
        return best

    def _evict(self, seq):
        """Preempt a running sequence IN PLACE (no retrace): free its
        pages, null its slot row, and requeue it right behind the head
        so it resumes by re-prefilling prompt+generated."""
        slot = seq.slot
        self.pool.free(seq.id)
        self._clear_slot(slot)
        seq.slot = None
        seq.evictions += 1
        seq.handle.evicted += 1
        self._queue.insert(1 if len(self._queue) >= 1 else 0, seq)
        self.stats["evictions"] += 1
        self._telemetry_count("kv_evictions_total")
        self._telemetry_gauge("kv_pages_in_use", self.pool.pages_in_use)
        self._telemetry_gauge("prefill_queue_depth", len(self._queue))

    def _clear_slot(self, slot):
        self._slot_seq[slot] = None
        self._page_table[slot] = 0
        self._seq_lens[slot] = 0
        self._last_tokens[slot] = 0
        self._active[slot] = False

    def _install(self, seq, slot):
        """Bucketed prefill + slot install: the prefill/decode
        disaggregation boundary.  Prefill compiles once per bucket
        (counted); the slot install is pure in-place data updates."""
        faultsim.inject("serve.prefill")
        t_pf0 = time.perf_counter()
        context = seq.context
        n = len(context)
        bucket = self._bucket_for(n)
        toks = onp.zeros((1, bucket), onp.int32)
        toks[0, :n] = context
        logits, k, v = self._prefill(bucket)(self.params,
                                             jnp.asarray(toks))
        self._note_program(("prefill", bucket))
        with self._lock:
            self.stats["prefills"] += 1
            if not seq.counted_admit:
                seq.counted_admit = True
                self.stats["admitted"] += 1
        first = int(onp.asarray(logits[0, n - 1]).argmax())
        self.pool.alloc(seq.id, seq.budget_tokens)
        self.pool.write_prompt(seq.id, k[:, 0, :n], v[:, 0, :n])
        now = time.monotonic()
        was_first = seq.t_first is None
        if was_first:
            seq.t_first = now
            seq.handle.ttft_ms = (now - seq.t_submit) * 1e3
            with self._lock:
                self._ttft_ms.append(seq.handle.ttft_ms)
        if seq.trace is not None:
            # TTFT decomposition for a traced request: admission wait
            # (submit -> prefill start, first install only) and the
            # bucketed prefill itself
            from .. import telemetry

            rl = telemetry.current()
            if rl is not None:
                t_pf1 = time.perf_counter()
                ctx = seq.trace
                if was_first:
                    seq.t_first_pc = t_pf1
                    rl.span("gen_admit", seq.t_submit_pc, t_pf0,
                            trace_id=ctx.trace_id,
                            span_id=_tracing.new_span_id(),
                            parent_span_id=ctx.span_id, flush=False)
                rl.span("gen_prefill", t_pf0, t_pf1,
                        trace_id=ctx.trace_id,
                        span_id=_tracing.new_span_id(),
                        parent_span_id=ctx.span_id, flush=False,
                        bucket=int(bucket),
                        reprefill=bool(seq.evictions))
        seq.generated.append(first)
        with self._lock:
            self.stats["tokens"] += 1
        self._telemetry_count("serve_tokens_total")
        self._telemetry_gauge("kv_pages_in_use", self.pool.pages_in_use)
        if self._seq_done(seq):
            self._finish_seq(seq, slot=None)
            return
        seq.slot = slot
        self._slot_seq[slot] = seq
        self._page_table[slot] = self.pool.page_table_row(
            seq.id, self.max_pages)
        self._seq_lens[slot] = n
        self._last_tokens[slot] = first
        self._active[slot] = True
        with self._lock:
            in_flight = int(self._active.sum())
            self.stats["max_in_flight"] = max(
                self.stats["max_in_flight"], in_flight)

    def _seq_done(self, seq):
        if len(seq.generated) >= seq.max_new:
            return True
        return self.eos_id is not None and \
            seq.generated[-1] == self.eos_id

    def _finish_seq(self, seq, slot):
        self.pool.free(seq.id)
        if slot is not None:
            self._clear_slot(slot)
        seq.handle.latency_ms = (time.monotonic() - seq.t_submit) * 1e3
        if seq.trace is not None:
            from .. import telemetry

            rl = telemetry.current()
            if rl is not None:
                t1 = time.perf_counter()
                ctx = seq.trace
                if seq.t_first_pc is not None and t1 > seq.t_first_pc:
                    rl.span("gen_decode", seq.t_first_pc, t1,
                            trace_id=ctx.trace_id,
                            span_id=_tracing.new_span_id(),
                            parent_span_id=ctx.span_id, flush=False,
                            tokens=len(seq.generated),
                            evictions=int(seq.evictions))
                _tracing.emit_span("gen_request", seq.t_submit_pc, t1,
                                   ctx, kind="server",
                                   tokens=len(seq.generated))
        with self._lock:
            self.stats["completed"] += 1
            self._latency_ms.append(seq.handle.latency_ms)
        seq.handle._finish(tokens=list(seq.generated))
        self._telemetry_gauge("kv_pages_in_use", self.pool.pages_in_use)

    # ------------------------------------------------------ the loop
    def _loop(self):
        while not self._stop:
            try:
                if self._breaker_open:
                    self._try_rewarm()
                    time.sleep(0.002)
                    continue
                self._admit()
                if self._active.any():
                    self._step_once()
                elif not self._queue:
                    time.sleep(0.001)
            except Exception:  # the loop must survive anything
                time.sleep(0.005)

    def _step_once(self):
        try:
            next_np = self._decode_state_step()
        except Exception as exc:
            self._model_failure(exc)
            return
        self._fail_count = 0
        stepped = [(slot, seq)
                   for slot, seq in enumerate(list(self._slot_seq))
                   if seq is not None and self._active[slot]]
        # count BEFORE finishing any handle: a caller woken by
        # result() must never read a stats snapshot missing this step
        if stepped:
            with self._lock:
                self.stats["tokens"] += len(stepped)
            self._telemetry_count("serve_tokens_total", len(stepped))
        for slot, seq in stepped:
            seq.generated.append(int(next_np[slot]))
            if self._seq_done(seq):
                self._finish_seq(seq, slot)

    def _model_failure(self, exc):
        with self._lock:
            self._fail_count += 1
            self.stats["decode_failures"] += 1
            trip = self._fail_count >= self.breaker_limit \
                and not self._breaker_open
            if trip:
                self._breaker_open = True
                self.stats["breaker_trips"] += 1
                self._rewarm_at = time.monotonic() + \
                    self._rewarm_backoff
        if not trip:
            return
        self._telemetry_count("serve_breaker_trips")
        # in-flight sequences fail STRUCTURED and every page comes
        # back — the no-leak invariant chaos asserts
        for slot, seq in enumerate(list(self._slot_seq)):
            if seq is None:
                continue
            seq.handle._finish(err=self._reject("model_error",
                                                repr(exc)))
            self._clear_slot(slot)
        with self._lock:
            queued, self._queue = list(self._queue), \
                collections.deque()
        for seq in queued:
            seq.handle._finish(err=self._reject(
                "breaker_open", "breaker tripped while queued"))
        self.pool.reset()
        self._telemetry_gauge("kv_pages_in_use", self.pool.pages_in_use)
        self._telemetry_gauge("prefill_queue_depth", 0)

    def _try_rewarm(self):
        if time.monotonic() < self._rewarm_at:
            return
        try:
            self._decode_state_step()  # all slots inactive: a probe
        except Exception:
            self._rewarm_backoff = min(self._rewarm_backoff * 2, 2.0)
            self._rewarm_at = time.monotonic() + self._rewarm_backoff
            return
        with self._lock:
            self._breaker_open = False
            self._fail_count = 0
            self._rewarm_backoff = 0.05

    # ------------------------------------------------------- reporting
    def in_flight(self):
        return int(self._active.sum())

    def report(self):
        """Snapshot + one ``generate`` run-log record: the telemetry
        contract of the generative path (schema.GENERATE_FIELDS)."""
        from ..telemetry.opstats import percentile

        with self._lock:
            st = {k: (dict(v) if isinstance(v, dict) else v)
                  for k, v in self.stats.items()}
            ttft = sorted(self._ttft_ms)
            wall = max(time.monotonic() - self._t_start, 1e-9)
        rep = {
            "name": self.name,
            "tokens": st["tokens"],
            "tokens_s": round(st["tokens"] / wall, 2),
            "ttft_p50_ms": round(percentile(ttft, 0.50), 3)
            if ttft else None,
            "ttft_p99_ms": round(percentile(ttft, 0.99), 3)
            if ttft else None,
            "in_flight": self.in_flight(),
            "max_in_flight": st["max_in_flight"],
            "evictions": st["evictions"],
            "shed": st["shed"],
            "pages_in_use": self.pool.pages_in_use,
            "queue_depth": len(self._queue),
            "kv_dtype": self.pool.dtype,
            "compiles": st["compiles"],
        }
        try:
            from .. import telemetry

            telemetry.generate(**rep)
        except Exception:
            pass
        return rep

    def drain(self, timeout=10.0):
        """Stop admission, let queued + in-flight sequences finish."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                idle = not self._queue and not self._active.any()
            if idle:
                return True
            time.sleep(0.005)
        return False

    def close(self):
        with self._lock:
            self._stop = True
            self._draining = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            leftovers = list(self._queue)
            self._queue.clear()
            slot_seqs = [s for s in self._slot_seq if s is not None]
        for seq in leftovers + slot_seqs:
            seq.handle._finish(err=ServeRejected(
                "shutdown", "server closed"))
        if self.pool is not None:
            self.pool.reset()
        self._started = False
