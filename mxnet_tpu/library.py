"""Custom-operator library loading — the MXLoadLib analog.

Reference: include/mxnet/lib_api.h + python/mxnet/library.py — the
reference dlopens a C++ .so whose ``RegisterOp`` entry points add
operators at runtime.  TPU-native translation: a plugin is a Python
module (file path or import name) whose ops are jnp/lax/Pallas
functions registered with ``mxnet_tpu.register_op`` — Pallas kernels
ARE the TPU's native "custom kernel .so", and the registry is the same
one every built-in op uses, so loaded ops appear in mx.nd / mx.sym /
mx.np namespaces immediately.

A plugin module may either:
  * call ``mxnet_tpu.ops.registry.register_op`` at import time, or
  * define ``register_ops(registry)`` which is called with the
    registry module after import (the lib_api.h ``initialize`` hook).

    # my_ops.py
    import jax.numpy as jnp
    def register_ops(registry):
        @registry.register_op("my_scaled_gelu")
        def my_scaled_gelu(x, *, scale=1.0):
            import jax
            return jax.nn.gelu(x) * scale

    mx.library.load("my_ops.py")
    mx.nd.my_scaled_gelu(mx.nd.ones((2, 2)), scale=0.5)
"""
from __future__ import annotations

import importlib
import importlib.util
import os
import sys

from .base import MXNetError

__all__ = ["load", "compiled_with_cxx11_abi", "loaded_libraries"]

_LOADED: dict[str, object] = {}


def load(path, verbose=True):
    """Load an operator plugin (reference MXLoadLib, library.py:29).

    ``path``: a ``.py`` file path or an importable module name.
    Returns the loaded module; ops it registers become visible in the
    nd/sym/np namespaces right away.
    """
    from .ops import registry as _registry

    key = os.path.abspath(path) if os.path.isfile(path) else path
    if key in _LOADED:
        return _LOADED[key]
    before = set(_registry.list_ops())
    if os.path.isfile(path):
        name = "_mx_plugin_" + os.path.splitext(
            os.path.basename(path))[0]
        spec = importlib.util.spec_from_file_location(name, path)
        if spec is None or spec.loader is None:
            raise MXNetError(f"cannot load library {path!r}")
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        try:
            spec.loader.exec_module(mod)
        except Exception as e:
            sys.modules.pop(name, None)
            raise MXNetError(
                f"library {path!r} failed to initialize: {e}") from e
    else:
        try:
            mod = importlib.import_module(path)
        except ImportError as e:
            raise MXNetError(
                f"{path!r} is neither a file nor an importable "
                f"module: {e}") from e
    hook = getattr(mod, "register_ops", None)
    if callable(hook):
        hook(_registry)
    new_ops = sorted(set(_registry.list_ops()) - before)
    if not new_ops:
        raise MXNetError(
            f"library {path!r} registered no operators (define "
            "register_ops(registry) or call register_op at import)")
    # expose in the generated namespaces (same path the built-in
    # registry uses at import time)
    from . import ndarray as _nd

    _nd._expose_new_ops()
    from .symbol import _op_namespace as _symns

    _symns._expose_new_ops()
    if verbose:
        print(f"[mx.library] loaded {path!r}: {', '.join(new_ops)}")
    _LOADED[key] = mod
    return mod


def loaded_libraries():
    return dict(_LOADED)


def compiled_with_cxx11_abi():
    """Reference library.py surface; the TPU build has no C++ ABI
    boundary for op plugins (they are jnp/Pallas python modules)."""
    return False
