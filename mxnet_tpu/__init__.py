"""mxnet_tpu — a TPU-native framework with the capabilities of Apache MXNet.

Brand-new design for JAX/XLA/Pallas/pjit (see SURVEY.md in the repo root):
  * ``mx.nd``       eager NDArray ops (XLA async dispatch = the engine)
  * ``mx.autograd`` imperative tape over jax.vjp
  * ``mx.gluon``    Block/HybridBlock (hybridize() -> jax.jit), Trainer
  * ``mx.sym``/``mx.mod``  symbolic front-end + Module shim over jit
  * ``mx.kvstore``  data-parallel comms over XLA collectives
  * ``mx.parallel`` TPU-first parallelism (mesh/dp/tp/sp utilities)

Typical use matches the reference:
    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.gpu(0))   # gpu() == TPU chip
"""
__version__ = "0.1.0"

from . import base  # noqa: F401
from . import config  # noqa: F401

if config.get_env("MXNET_ENFORCE_DETERMINISM"):
    import jax as _jax

    _jax.config.update("jax_default_matmul_precision", "highest")

if config.get_env("MXNET_PROFILER_AUTOSTART"):
    from . import profiler as _profiler_autostart

    _profiler_autostart.set_state("run")
from .base import MXNetError  # noqa: F401
from .context import (  # noqa: F401
    Context,
    cpu,
    cpu_pinned,
    current_context,
    gpu,
    num_gpus,
    num_tpus,
    tpu,
)
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from . import autograd  # noqa: F401
from . import random  # noqa: F401
from .util import is_np_array, set_np, use_np  # noqa: F401

# Subpackages added as milestones land (gluon, symbol, module, kvstore,
# optimizer, metric, io, parallel) are imported lazily below to keep import
# errors local while the framework is being built out.
import importlib as _importlib

_LAZY = {
    "gluon": ".gluon",
    "sym": ".symbol",
    "symbol": ".symbol",
    "mod": ".module",
    "module": ".module",
    "kvstore": ".kvstore",
    "kv": ".kvstore",
    "optimizer": ".optimizer",
    "metric": ".metric",
    "io": ".io",
    "image": ".image",
    "init": ".initializer",
    "initializer": ".initializer",
    "lr_scheduler": ".lr_scheduler",
    "callback": ".callback",
    "parallel": ".parallel",
    "profiler": ".profiler",
    "runtime": ".runtime",
    "test_utils": ".test_utils",
    "recordio": ".recordio",
    "model": ".model",
    "monitor": ".monitor",
    "visualization": ".visualization",
    "viz": ".visualization",
    "np": ".numpy",
    "npx": ".numpy_extension",
    "engine": ".engine",
    "contrib": ".contrib",
    "amp": ".contrib.amp",
    "operator": ".operator",
    "rtc": ".rtc",
    "library": ".library",
    "deploy": ".deploy",
    "quantization": ".quantization",
    "resilience": ".resilience",
    "serving": ".serving",
    "telemetry": ".telemetry",
}


def __getattr__(name):
    if name in _LAZY:
        mod = _importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    if name == "AttrScope":  # reference surface: mx.AttrScope
        from .symbol import AttrScope

        globals()[name] = AttrScope
        return AttrScope
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")
