"""Ring attention — context/sequence parallelism over the device mesh.

SURVEY.md §5.7 TPU-native mandate: sequence scaling comes from sharding
the sequence axis over a mesh axis and rotating K/V blocks around the
ring with ``lax.ppermute`` while queries stay put — each device only
ever holds S/n keys, so attention memory is O(S/n) per chip and the
permutes ride the ICI torus.  The online-softmax accumulator (m, l,
acc) makes the blockwise combination exact, the same trick the local
flash kernel uses (ops/flash_attention.py).

Public API:
  ring_attention(q, k, v, mesh, axis_name="seq", causal=False)
      — shard_map'd exact attention; q/k/v are (batch, heads, seq, d)
        GLOBAL arrays (sharded or to-be-sharded on seq).
  ring_attention_sharded(...)
      — the per-device body, for composition inside existing
        shard_map/pjit programs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_sharded"]


def ring_attention_sharded(q, k, v, axis_name, causal=False,
                           sm_scale=None, axis_size=None):
    """Per-device ring attention body (call inside shard_map).

    q, k, v: (batch, heads, seq_local, d) local shards; the sequence
    axis is sharded over ``axis_name``.  Returns the local output
    shard.  Exact: the K/V ring rotation + online softmax reproduces
    full softmax(QK^T)V.

    axis_size: static ring length; required on jax 0.4.x, where
    ``lax.axis_size`` does not exist (the scan length and permutation
    table below must be static, so a traced psum-of-1 cannot stand in).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = int(axis_size) if axis_size is not None \
        else lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape

    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)
    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)

    q_pos = my * s_loc + jnp.arange(s_loc)  # global query positions

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        # k block currently held came from device (my - i) mod n
        src = (my - i) % n
        k_pos = src * s_loc + jnp.arange(s_loc)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf,
                       k_cur.astype(jnp.float32)) * sm_scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s),
                      jnp.exp(s - m_safe[..., None]), 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        # rotate the k/v ring one hop (ICI neighbor exchange)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l, acc, k_nxt, v_nxt), None

    # scan (not fori_loop): the online-softmax carry must be reverse-
    # mode differentiable for the backward pass
    (m, l, acc, _, _), _ = lax.scan(step, (m, l, acc, k, v),
                                    jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


@functools.lru_cache(maxsize=64)
def _build_ring_fn(mesh, axis_name, causal, sm_scale):
    from . import compat_shard_map

    spec = P(None, None, axis_name, None)
    fn = functools.partial(ring_attention_sharded, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale,
                           axis_size=mesh.shape[axis_name])
    mapped = compat_shard_map(
        lambda q_, k_, v_: fn(q_, k_, v_),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return jax.jit(
        mapped,
        in_shardings=(NamedSharding(mesh, spec),) * 3,
        out_shardings=NamedSharding(mesh, spec))


def ring_attention(q, k, v, mesh, axis_name="seq", causal=False,
                   sm_scale=None):
    """Exact attention with the sequence axis sharded over
    ``mesh[axis_name]`` — O(seq/n) activation memory per device.

    The jitted shard_map program is cached per (mesh, axis, causal,
    scale) so repeated calls hit the compilation cache."""
    fn = _build_ring_fn(mesh, axis_name, bool(causal),
                        float(sm_scale) if sm_scale is not None else None)
    return fn(q, k, v)
