"""Pipeline parallelism — GPipe-style microbatch schedule over the mesh.

The reference has no pipeline engine (its model parallelism is manual
device placement, example/model-parallel); on TPU, pipeline parallelism
is a first-class axis of the sharding design (SURVEY.md §5.8): stage
parameters live stacked on a leading ``n_stages`` axis sharded over a
'pipe' mesh axis, microbatch activations rotate stage-to-stage with
``lax.ppermute`` hops that ride the ICI torus, and the whole schedule is
one ``lax.scan`` inside one ``shard_map`` — a single XLA program, fully
differentiable, so fwd+bwd+optimizer still fuse into one step.

Schedule: classic GPipe fill-and-drain.  For S stages and M
microbatches the scan runs ``M + S - 1`` ticks; at tick t stage 0
injects microbatch t (while t < M) and stage S-1 retires microbatch
t-(S-1) (once t >= S-1).  Bubble fraction is (S-1)/(M+S-1) — pick
M >> S.

Constraint (standard for pipelined transformer stacks): every stage
maps activations of one fixed shape to the same shape, so the rotating
buffer is static-shaped for XLA.  The stage body itself is arbitrary
traceable code.

Public API:
  pipeline_apply(stage_fn, stacked_params, x, mesh, ...)
      — run the pipeline over GLOBAL inputs; returns global outputs.
  pipeline_apply_sharded(...)
      — the per-device body, for composition inside an existing
        shard_map program (e.g. combined dp×pp meshes).
  stack_stage_params(param_dicts)
      — stack per-stage parameter pytrees onto the leading stage axis.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["pipeline_apply", "pipeline_apply_sharded",
           "stack_stage_params"]


def stack_stage_params(param_dicts):
    """Stack a list of per-stage parameter pytrees (identical
    structure) into one pytree with a leading ``n_stages`` axis —
    the axis that shards over the 'pipe' mesh dimension."""
    if not param_dicts:
        raise ValueError("need at least one stage")
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *param_dicts)


def pipeline_apply_sharded(stage_fn, params, x, axis_name,
                           n_microbatches, axis_size=None):
    """Per-device GPipe body (call inside shard_map).

    params: this device's stage parameters with a leading local-stage
    axis of size 1 (the 'pipe'-sharded slice of the stacked pytree).
    x: the FULL batch (replicated across the pipe axis); reshaped to
    (M, mb, ...) microbatches internally.  Returns the full output
    batch, replicated (psum-masked from the last stage).

    axis_size: static pipe depth; required on jax 0.4.x, where
    ``lax.axis_size`` does not exist (the tick count and permutation
    table must be static).
    """
    n = int(axis_size) if axis_size is not None \
        else lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    p = jax.tree_util.tree_map(lambda a: a[0], params)

    batch = x.shape[0]
    if batch % n_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by n_microbatches "
            f"{n_microbatches}")
    mb = batch // n_microbatches
    mbs = x.reshape((n_microbatches, mb) + x.shape[1:])

    state = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    out = jnp.zeros_like(mbs)
    n_ticks = n_microbatches + n - 1

    def tick(carry, t):
        state, out = carry
        # stage 0 injects microbatch t (clipped load; masked select)
        inj = lax.dynamic_index_in_dim(
            mbs, jnp.clip(t, 0, n_microbatches - 1), 0, keepdims=False)
        inp = jnp.where(jnp.logical_and(idx == 0, t < n_microbatches),
                        inj, state)
        y = stage_fn(p, inp)
        # last stage retires microbatch t-(n-1) once the pipe is full
        slot = jnp.clip(t - (n - 1), 0, n_microbatches - 1)
        retired = lax.dynamic_update_index_in_dim(out, y, slot, 0)
        out = jnp.where(jnp.logical_and(idx == n - 1, t >= n - 1),
                        retired, out)
        # rotate activations one hop down the pipe (ICI neighbor hop);
        # stage 0's incoming value is ignored — it always injects
        perm = [(j, (j + 1) % n) for j in range(n)]
        state = lax.ppermute(y, axis_name, perm)
        return (state, out), None

    (_, out), _ = lax.scan(tick, (state, out), jnp.arange(n_ticks))
    # only the last stage holds real outputs; psum-mask replicates them
    out = lax.psum(jnp.where(idx == n - 1, out, jnp.zeros_like(out)),
                   axis_name)
    return out.reshape(x.shape)


@functools.lru_cache(maxsize=64)
def _build_pipeline_fn(stage_fn, mesh, axis_name, n_microbatches,
                       treedef, leaf_ndims, x_ndim):
    from . import compat_shard_map

    param_spec = treedef.unflatten(
        [P(axis_name, *([None] * (nd - 1))) for nd in leaf_ndims])
    x_spec = P(*([None] * x_ndim))

    def body(params, x):
        return pipeline_apply_sharded(stage_fn, params, x, axis_name,
                                      n_microbatches,
                                      axis_size=mesh.shape[axis_name])

    mapped = compat_shard_map(body, mesh=mesh,
                              in_specs=(param_spec, x_spec),
                              out_specs=x_spec, check_vma=False)
    return jax.jit(mapped)


def pipeline_apply(stage_fn, stacked_params, x, mesh, axis_name="pipe",
                   n_microbatches=None):
    """Run ``stage_fn`` as an ``n_stages``-deep pipeline over
    ``mesh[axis_name]``.

    stage_fn(stage_params, act) -> act : one stage, shape-preserving.
    stacked_params: pytree with leading axis n_stages == mesh size on
    ``axis_name`` (see stack_stage_params).
    x: (batch, ...) global input; n_microbatches must divide batch
    (default: 4 microbatches per stage).
    """
    n = mesh.shape[axis_name]
    if n_microbatches is None:
        n_microbatches = 4 * n
    leaves = jax.tree_util.tree_leaves(stacked_params)
    for leaf in leaves:
        if leaf.shape[0] != n:
            raise ValueError(
                f"stacked param leading axis {leaf.shape[0]} != pipe "
                f"size {n}")
    leaves, treedef = jax.tree_util.tree_flatten(stacked_params)
    fn = _build_pipeline_fn(
        stage_fn, mesh, axis_name, int(n_microbatches), treedef,
        tuple(leaf.ndim for leaf in leaves), x.ndim)
    stacked_params = jax.device_put(
        stacked_params,
        jax.tree_util.tree_map(
            lambda leaf: NamedSharding(
                mesh, P(axis_name, *([None] * (leaf.ndim - 1)))),
            stacked_params))
    return fn(stacked_params, x)
