"""TPU-first parallelism (SURVEY.md §2.5 TPU-native equivalent).

The reference scales via DataParallelExecutorGroup (batch slicing across
GPUs, python/mxnet/module/executor_group.py:144) + KVStore reduce trees +
ps-lite servers.  The TPU-native design replaces all of that with ONE
compiled SPMD program over a ``jax.sharding.Mesh``:

  * dp  — batch axis sharded over 'data'; XLA inserts the gradient psum
          (the entire KVStore 'device'/'nccl'/'dist_sync' stack).
  * tp  — weight axes sharded over 'model' (absent in the reference —
          modern requirement).
  * sp  — sequence axis sharded over 'seq' (ring attention lives in
          mxnet_tpu.parallel.ring).
  * Optimizer state shards with the params (ZeRO ≡ the reference's
    server-side optimizer, kvstore_dist_server.h:346).

`functionalize` turns a Gluon Block into (params pytree, pure apply_fn) —
the bridge from the imperative API to pjit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import _rng, autograd
from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["get_mesh", "functionalize", "make_train_step",
           "DataParallelTrainer", "Mesh", "NamedSharding", "P",
           "NORM_STAT_SUFFIXES", "amp_cast_params"]

#: parameter-name suffixes that stay fp32 under mixed precision (the AMP
#: policy the reference encodes in contrib/amp/lists: norm affine+stats)
NORM_STAT_SUFFIXES = ("gamma", "beta", "running_mean", "running_var",
                      "moving_mean", "moving_var")


def _is_norm_stat(name):
    return any(name.endswith(s) for s in NORM_STAT_SUFFIXES)


def amp_cast_params(params, compute_dtype):
    """Cast a {name: array} tree to the compute dtype, keeping norm
    affine/stat parameters in their original (fp32) dtype."""
    if compute_dtype is None:
        return params
    return {n: (v if _is_norm_stat(n) else v.astype(compute_dtype))
            for n, v in params.items()}


def get_mesh(shape=None, axis_names=("data",), devices=None):
    """Build a Mesh over the available devices.

    get_mesh() -> 1-D 'data' mesh over all devices;
    get_mesh((2, 4), ('data', 'model')) -> dp×tp grid.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    arr = onp.array(devices[: int(onp.prod(shape))]).reshape(shape)
    return Mesh(arr, axis_names)


def functionalize(block, train=False):
    """Extract (params, apply_fn) from a Gluon block.

    params: {flat_name: jax.Array} in deterministic order.
    apply_fn(params, *inputs, key=None): pure — swaps the traced values
    into the block (same mechanism as HybridBlock._call_cached) and runs
    the imperative forward, so ANY Block works, hybridized or not.
    """
    from ..gluon.block import _collect_all_params, _swap_param_values

    flat_params = _collect_all_params(block)
    names = []
    seen = {}
    for p in flat_params:
        name = p.name
        if name in seen:  # shared params appear once
            continue
        seen[name] = p
        names.append(name)
    params = {n: seen[n].data()._data for n in names}

    def apply_fn(param_dict, *inputs, key=None):
        if key is None:
            key = jax.random.key(0)
        vals = [param_dict[p.name] for p in flat_params]
        with _rng.trace_key_scope(key), autograd._Scope(False, train):
            saved = _swap_param_values(block, vals)
            try:
                args = [
                    nd.NDArray(x) if not isinstance(x, nd.NDArray) else x
                    for x in inputs
                ]
                out = block(*args)
            finally:
                _swap_param_values(block, saved)
        if isinstance(out, (list, tuple)):
            return [o._data for o in out]
        return out._data

    return params, apply_fn


def _sgd_tree_update(params, grads, mom, lr, momentum, wd):
    new_mom = jax.tree_util.tree_map(
        lambda m, g, w: momentum * m + g + wd * w, mom, grads, params)
    new_params = jax.tree_util.tree_map(
        lambda w, m: w - lr * m, params, new_mom)
    return new_params, new_mom


def _adam_tree_update(params, grads, state, lr, b1, b2, eps, wd, t):
    m, v = state
    # couple wd into the gradient BEFORE the moment updates — same rule
    # as the eager Adam optimizer (optimizer.py _adam_step) and the
    # reference's adam_update op, so both paths train identically
    grads = jax.tree_util.tree_map(lambda g, w: g + wd * w, grads, params)
    new_m = jax.tree_util.tree_map(
        lambda mm, g: b1 * mm + (1 - b1) * g, m, grads)
    new_v = jax.tree_util.tree_map(
        lambda vv, g: b2 * vv + (1 - b2) * g * g, v, grads)
    lr_t = lr * jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
    new_p = jax.tree_util.tree_map(
        lambda w, mm, vv: w - lr_t * mm / (jnp.sqrt(vv) + eps),
        params, new_m, new_v)
    return new_p, (new_m, new_v)


def make_train_step(block, loss_fn, optimizer="sgd", learning_rate=0.01,
                    momentum=0.9, wd=0.0, beta1=0.9, beta2=0.999,
                    epsilon=1e-8, mesh=None, data_axis="data",
                    param_spec=None, donate=True, compute_dtype=None):
    """Build ONE fully-fused jitted SPMD train step.

    Returns (step_fn, params, opt_state) where
      step_fn(params, opt_state, x, y, key, t) -> (loss, params, opt_state)

    The whole forward+backward+optimizer compiles into a single XLA
    program (the analog of GraphExecutor's full fwd+bwd graph plus the
    fused optimizer kernels, graph_executor.cc:416 +
    src/operator/optimizer_op.cc).  Under a mesh, x/y shard on the batch
    axis and params replicate (or shard per `param_spec` for tp/ZeRO);
    XLA inserts the gradient all-reduce over ICI.
    """
    params, apply_fn = functionalize(block, train=True)
    if mesh is None:
        # commit params to the accelerator once; otherwise every step
        # re-streams them host->HBM (Context default is cpu for reference
        # parity, but the fused step must live in device memory)
        dev = jax.devices()[0]
        params = jax.device_put(params, dev)

    def loss_of(param_dict, x, y, key):
        if compute_dtype is not None:
            # AMP policy (reference contrib/amp list semantics): matmul/
            # conv weights in bf16, norm affine+stats in fp32
            param_dict = amp_cast_params(param_dict, compute_dtype)
            x = x.astype(compute_dtype)
        out = apply_fn(param_dict, x, key=key)
        loss_nd = loss_fn(nd.NDArray(out.astype(jnp.float32)),
                          nd.NDArray(y))
        return jnp.mean(loss_nd._data)

    if optimizer == "sgd":
        opt_state = jax.tree_util.tree_map(jnp.zeros_like, params)

        def step(params_, opt_state_, x, y, key, t):
            loss, grads = jax.value_and_grad(loss_of)(params_, x, y, key)
            new_p, new_m = _sgd_tree_update(
                params_, grads, opt_state_, learning_rate, momentum, wd)
            return loss, new_p, new_m

    elif optimizer == "adam":
        opt_state = (
            jax.tree_util.tree_map(jnp.zeros_like, params),
            jax.tree_util.tree_map(jnp.zeros_like, params),
        )

        def step(params_, opt_state_, x, y, key, t):
            loss, grads = jax.value_and_grad(loss_of)(params_, x, y, key)
            new_p, new_s = _adam_tree_update(
                params_, grads, opt_state_, learning_rate, beta1, beta2,
                epsilon, wd, t)
            return loss, new_p, new_s

    else:
        raise MXNetError(f"fused step supports sgd/adam, got {optimizer}")

    donate_argnums = (0, 1) if donate else ()
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        batch_sharding = NamedSharding(mesh, P(data_axis))
        if param_spec is None:
            p_shard = jax.tree_util.tree_map(lambda _: repl, params)
            opt_shard = jax.tree_util.tree_map(lambda _: repl, opt_state)
        else:
            p_shard = {
                n: NamedSharding(mesh, param_spec.get(n, P()))
                for n in params
            }
            # optimizer state (per-param moments) shards like its param
            if isinstance(opt_state, tuple):
                opt_shard = tuple(
                    {n: p_shard[n] for n in params} for _ in opt_state)
            else:
                opt_shard = {n: p_shard[n] for n in params}
        step_fn = jax.jit(
            step,
            in_shardings=(p_shard, opt_shard, batch_sharding,
                          batch_sharding, None, None),
            out_shardings=(None, p_shard, opt_shard),
            donate_argnums=donate_argnums,
        )
        params = jax.device_put(params, p_shard)
        opt_state = jax.device_put(opt_state, opt_shard)
    else:
        step_fn = jax.jit(step, donate_argnums=donate_argnums,
                          static_argnums=())
    return step_fn, params, opt_state


class DataParallelTrainer:
    """High-level fused data-parallel training driver.

    The TPU-native replacement for Module+DataParallelExecutorGroup+
    KVStore: one object owning the sharded params/opt state and a
    compiled SPMD step.  Call ``fit_batch(x, y)`` per batch;
    ``sync_to_block()`` writes weights back into the Gluon block for
    checkpointing/eval via the normal APIs.
    """

    def __init__(self, block, loss_fn, optimizer="sgd", mesh=None,
                 **opt_kwargs):
        self._block = block
        self._mesh = mesh
        self._step_fn, self._params, self._opt_state = make_train_step(
            block, loss_fn, optimizer=optimizer, mesh=mesh, **opt_kwargs)
        self._t = 0
        self._key = jax.random.key(0)

    def fit_batch(self, x, y):
        x = x._data if isinstance(x, nd.NDArray) else jnp.asarray(x)
        y = y._data if isinstance(y, nd.NDArray) else jnp.asarray(y)
        self._t += 1
        self._key, sub = jax.random.split(self._key)
        loss, self._params, self._opt_state = self._step_fn(
            self._params, self._opt_state, x, y, sub, float(self._t))
        return loss

    @property
    def params(self):
        return self._params

    def sync_to_block(self):
        from ..gluon.block import _collect_all_params

        for p in _collect_all_params(self._block):
            if p.name in self._params:
                # gather off the mesh so eager single-device ops work
                v = jnp.asarray(onp.asarray(self._params[p.name]))
                p.data()._adopt(v)
