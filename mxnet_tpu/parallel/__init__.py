"""TPU-first parallelism (SURVEY.md §2.5 TPU-native equivalent).

The reference scales via DataParallelExecutorGroup (batch slicing across
GPUs, python/mxnet/module/executor_group.py:144) + KVStore reduce trees +
ps-lite servers.  The TPU-native design replaces all of that with ONE
compiled SPMD program over a ``jax.sharding.Mesh``:

  * dp  — batch axis sharded over 'data'; XLA inserts the gradient psum
          (the entire KVStore 'device'/'nccl'/'dist_sync' stack).
  * tp  — weight axes sharded over 'model' (absent in the reference —
          modern requirement).
  * sp  — sequence axis sharded over 'seq' (ring attention lives in
          mxnet_tpu.parallel.ring).
  * pp  — GPipe microbatch pipeline over a 'pipe' axis
          (mxnet_tpu.parallel.pipeline).
  * ep  — mixture-of-experts routing over an 'expert' axis
          (mxnet_tpu.parallel.moe).
  * Optimizer state shards with the params (ZeRO ≡ the reference's
    server-side optimizer, kvstore_dist_server.h:346).

`functionalize` turns a Gluon Block into (params pytree, pure apply_fn) —
the bridge from the imperative API to pjit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import _rng, autograd
from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["get_mesh", "functionalize", "make_train_step",
           "DataParallelTrainer", "Mesh", "NamedSharding", "P",
           "NORM_STAT_SUFFIXES", "amp_cast_params", "auto_tp_spec",
           "ring", "pipeline", "moe", "zero", "compat_shard_map",
           "make_predict_fn", "tune_microbatch"]


def compat_shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` with one signature across jax releases: it
    graduated from ``jax.experimental.shard_map`` (kwarg ``check_rep``)
    to top-level ``jax.shard_map`` (kwarg ``check_vma``) — 0.4.x wheels
    only carry the former."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)

#: parameter-name suffixes that stay fp32 under mixed precision (the AMP
#: policy the reference encodes in contrib/amp/lists: norm affine+stats)
NORM_STAT_SUFFIXES = ("gamma", "beta", "running_mean", "running_var",
                      "moving_mean", "moving_var")


def _is_norm_stat(name):
    return any(name.endswith(s) for s in NORM_STAT_SUFFIXES)


def amp_cast_params(params, compute_dtype):
    """Cast a {name: array} tree to the compute dtype, keeping norm
    affine/stat parameters in their original (fp32) dtype."""
    if compute_dtype is None:
        return params
    return {n: (v if _is_norm_stat(n) else v.astype(compute_dtype))
            for n, v in params.items()}


def get_mesh(shape=None, axis_names=("data",), devices=None):
    """Build a Mesh over the available devices.

    get_mesh() -> 1-D 'data' mesh over all devices;
    get_mesh((2, 4), ('data', 'model')) -> dp×tp grid.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    arr = onp.array(devices[: int(onp.prod(shape))]).reshape(shape)
    return Mesh(arr, axis_names)


def functionalize(block, train=False):
    """Extract (params, apply_fn) from a Gluon block.

    params: {flat_name: jax.Array} in deterministic order.
    apply_fn(params, *inputs, key=None): pure — swaps the traced values
    into the block (same mechanism as HybridBlock._call_cached) and runs
    the imperative forward, so ANY Block works, hybridized or not.
    """
    from ..gluon.block import _collect_all_params, _swap_param_values

    flat_params = _collect_all_params(block)
    names = []
    seen = {}
    for p in flat_params:
        name = p.name
        if name in seen:  # shared params appear once
            continue
        seen[name] = p
        names.append(name)
    params = {n: seen[n].data()._data for n in names}

    def apply_fn(param_dict, *inputs, key=None):
        if key is None:
            key = jax.random.key(0)
        vals = [param_dict[p.name] for p in flat_params]
        with _rng.trace_key_scope(key), autograd._Scope(False, train):
            saved = _swap_param_values(block, vals)
            try:
                args = [
                    nd.NDArray(x) if not isinstance(x, nd.NDArray) else x
                    for x in inputs
                ]
                out = block(*args)
            finally:
                _swap_param_values(block, saved)
        if isinstance(out, (list, tuple)):
            return [o._data for o in out]
        return out._data

    return params, apply_fn


def auto_tp_spec(block, tp_size, axis_name="model", min_dim=64):
    """Derive a tensor-parallel ``param_spec`` for a model-zoo network.

    Shards the leading (output-channel/units) axis of conv and dense
    weights over ``axis_name`` wherever it divides by ``tp_size`` and is
    at least ``min_dim`` (small layers replicate — the collective cost
    outweighs the split).  Norm statistics and biases replicate.  The
    reference has no TP (SURVEY.md §2.5: absent); this is the modern
    mandate's default policy, overridable per-param by the caller.
    """
    probe, _ = functionalize(block)
    spec = {}
    for name, v in probe.items():
        if _is_norm_stat(name) or name.endswith("_bias"):
            continue
        if name.endswith("_weight") and v.ndim >= 2 and \
                v.shape[0] % tp_size == 0 and v.shape[0] >= min_dim:
            spec[name] = P(*((axis_name,) + (None,) * (v.ndim - 1)))
    return spec


def _build_optimizer(optimizer, learning_rate, momentum, wd, beta1, beta2,
                     epsilon, opt_kwargs):
    """Resolve the ``optimizer`` argument to an Optimizer instance with a
    fused rule, filtering convenience kwargs to what its ctor accepts."""
    import inspect

    from .. import optimizer as opt_mod

    if isinstance(optimizer, opt_mod.Optimizer):
        if opt_kwargs:
            # same contract as gluon.Trainer: hyper-params belong to the
            # instance, silently dropping them would mislead
            raise MXNetError(
                "optimizer kwargs must not be given when optimizer is an "
                f"Optimizer instance (got {sorted(opt_kwargs)})")
        return optimizer
    klass = opt_mod.Optimizer.opt_registry.get(str(optimizer).lower())
    if klass is None:
        raise MXNetError(f"unknown optimizer {optimizer!r}")
    sig = inspect.signature(klass.__init__)
    accepted = set(sig.parameters)
    base_accepted = set(
        inspect.signature(opt_mod.Optimizer.__init__).parameters)
    if not any(p.kind is inspect.Parameter.VAR_KEYWORD
               for p in sig.parameters.values()):
        base_accepted = set()
    # the convenience defaults are filtered to what the ctor accepts;
    # explicit opt_kwargs must match exactly (typos should not pass)
    unknown = {k for k in opt_kwargs
               if k not in accepted and k not in base_accepted}
    if unknown:
        raise MXNetError(
            f"optimizer {optimizer!r} does not accept {sorted(unknown)}")
    kwargs = dict(learning_rate=learning_rate, wd=wd, momentum=momentum,
                  beta1=beta1, beta2=beta2, epsilon=epsilon)
    kwargs = {k: v for k, v in kwargs.items()
              if k in accepted or k in base_accepted}
    kwargs.update(opt_kwargs)
    return klass(**kwargs)


def make_train_step(block, loss_fn, optimizer="sgd", learning_rate=0.01,
                    momentum=0.9, wd=0.0, beta1=0.9, beta2=0.999,
                    epsilon=1e-8, mesh=None, data_axis="data",
                    param_spec=None, donate=True, compute_dtype=None,
                    loss_scale=None, sample_data=None, autotune=None,
                    variant_ops=None, nan_guard=None,
                    optimizer_sharding=None, bucket_bound=None,
                    zero_stage=None, gradient_compression=None,
                    **opt_kwargs):
    """Build ONE fully-fused jitted SPMD train step.

    Returns (step_fn, params, opt_state) where
      step_fn(params, opt_state, x, y, key, t) -> (loss, params, opt_state)

    The whole forward+backward+optimizer compiles into a single XLA
    program (the analog of GraphExecutor's full fwd+bwd graph plus the
    fused optimizer kernels, graph_executor.cc:416 +
    src/operator/optimizer_op.cc).  Under a mesh, x/y shard on the batch
    axis and params replicate (or shard per `param_spec` for tp/ZeRO);
    XLA inserts the gradient all-reduce over ICI.

    optimizer: any registry name ('sgd', 'adam', 'lars', 'ftml', ...) or
    an Optimizer instance — its pure ``fused_update`` rule is traced into
    the program (reference analog: server-side optimizer,
    kvstore_dist_server.h:346, and fused optimizer_op kernels).

    loss_scale: None, a static float, or 'dynamic' — dynamic loss scaling
    doubles the scale every 2000 consecutive finite steps and halves it
    on overflow, skipping the update (reference: contrib/amp loss scaler
    + all_finite, src/operator/contrib/all_finite.cc).

    donate=True (the default) donates the params/opt_state buffers to
    XLA: the step writes its updated state in place instead of
    allocating a second copy — the reference's ``static_alloc`` memory
    reuse (SURVEY §7 maps static_alloc ≈ donate_argnums).  The caller
    contract is the functional one this signature already imposes: the
    INPUT params/opt_state are dead after the call (you must thread the
    returned ones), donation just makes XLA exploit that.  Pass
    donate=False to keep calling with the same buffers (step-parity
    tests do).

    sample_data=(x, y): enables the in-step variant autotuner
    (mxnet_tpu.autotune, the cudnn_tune analog): each op in
    ``variant_ops`` races inside a jitted chained run of THIS step on
    the sample batch, the winner persists keyed on (op, batch shape,
    dtype, platform, mesh), and the returned step traces under it.
    On a warm cache the race is skipped (pure lookups).  autotune=None
    follows MXNET_AUTOTUNE; autotune=False disables for this step.
    Without sample_data no timing runs, but cached winners still apply
    to the returned step via the program scope.  In-step timing is
    single-device for now: under a mesh, sample_data warns and is
    ignored (mesh-keyed cached winners still apply).

    nan_guard: step-level NaN/Inf guard compiled INTO the program
    (skip-and-count, the same selection dynamic loss scaling uses): a
    step whose loss or any gradient is non-finite leaves params and
    optimizer state untouched, and ``opt_state['_bad_steps']`` counts
    CONSECUTIVE bad steps (reset to 0 by any finite step) so the host
    can enforce MXNET_BAD_STEP_LIMIT without a per-step sync.  None
    follows that env var (>0 arms it); dynamic loss scaling already
    skips non-finite updates, so the guard stays off there.

    optimizer_sharding="ps": the sharded-server gradient exchange
    (ZeRO-1 ≡ the reference's key-sharded servers running the
    server-side optimizer, kvstore_dist_server.h:346, see
    parallel.zero).  Gradients flatten into dtype-homogeneous flat
    buckets (split threshold: ``bucket_bound`` elements, default the
    authentic ``MXNET_KVSTORE_BIGARRAY_BOUND``), each bucket
    ``reduce_scatter``s over the data axis, the optimizer's fused rule
    updates ONLY the locally-owned shard (optimizer state is created,
    donated and persisted SHARDED — per-chip state bytes ~ params/N),
    and the updated param buckets ``all_gather`` back — ~2·buckets
    collectives per step instead of one all-reduce per parameter
    tensor.  ``None`` follows MXNET_OPTIMIZER_SHARDING ('ps' arms it,
    '0' force-disables, empty leaves it off); needs a mesh and does
    not compose with ``param_spec`` (tp) yet.  Dynamic loss scaling
    checks finiteness on the SCATTERED shard and psums the verdict;
    the nan-guard and donation contracts are unchanged; under the
    forward each device sees its local batch shard, so BatchNorm uses
    per-shard statistics — the reference DataParallel semantics
    (executor_group.py), vs the replicated path's SyncBatchNorm-style
    global stats.

    zero_stage: the ZeRO stage of the sharded exchange (1, 2 or 3;
    None follows MXNET_ZERO_STAGE, which overrides the argument, and
    defaults to stage 2).  Setting a stage opts the step into
    optimizer_sharding="ps" under a mesh.  Stage 1 is the classic
    ZeRO-1 exchange for ablation: one all-reduce per bucket, the
    owned shard sliced off the replicated reduced gradient.  Stage 2
    (the default — bit-for-bit the program this step has always
    traced) reduce-scatters each bucket so no device materializes
    full gradients.  Stage 3 additionally shards the PARAMETERS: the
    returned params pytree is ``{"_bucket<i>": flat padded bucket}``
    sharded over the data axis (per-chip param+state bytes ~ total/N),
    the forward all-gathers each bucket with all launches issued
    up-front so bucket k+1's gather overlaps bucket k's compute
    (prefetch), the backward's reduce-scatters fall out of
    differentiating through those gathers (interleaved with backward
    compute), and nothing gathers back.  Use
    ``zero.gather_stage3_params(step_fn.zero_plan, params)`` to
    reassemble the named tree; ``step_fn.zero_stage`` /
    ``step_fn.zero_plan`` expose the layout.

    gradient_compression: ``{"type": "2bit", "threshold": t}`` —
    2-bit quantization (kvstore.GradientCompression math) applied
    per-bucket on the scattered gradient shard before the optimizer,
    with the error-feedback residual carried SHARD-LOCAL in fp32
    inside opt_state (``_residual<i>``) so narrow-dtype buckets keep
    full-precision accumulation.  Requires optimizer_sharding="ps".
    """
    from .. import autotune as _at
    from ..config import setup_compilation_cache

    setup_compilation_cache()
    params, apply_fn = functionalize(block, train=True)
    if mesh is None:
        # commit params to the accelerator once; otherwise every step
        # re-streams them host->HBM (Context default is cpu for reference
        # parity, but the fused step must live in device memory)
        dev = jax.local_devices()[0]
        params = jax.device_put(params, dev)

    opt = _build_optimizer(optimizer, learning_rate, momentum, wd, beta1,
                           beta2, epsilon, opt_kwargs)

    if variant_ops is None:
        # default race roster: the conv 1x1 lowering always; the
        # dtype ladder joins only when the knob arms it, no explicit
        # compute_dtype pins the answer, AND the env carries no hand
        # override (MXNET_DTYPE_LADDER=bf16/fp8/fp32 already decided —
        # racing a pinned step to discard the result would waste a
        # compile per signature).  Which rungs race — fp32/bf16, or
        # fp8 too — is the knob's roster (autotune.ladder_rungs).
        variant_ops = ("conv1x1_dot",)
        if (compute_dtype is None and _at.dtype_ladder_armed()
                and _at.variant_choice("dtype_ladder") is None):
            variant_ops += ("dtype_ladder",)

    def _ladder_arm():
        """The dtype-ladder decision for THIS trace (None = ladder not
        consulted): an explicit compute_dtype always wins; otherwise a
        tuner force scope, the MXNET_DTYPE_LADDER hand override, or
        the cached per-program winner applied via program_scope."""
        if compute_dtype is not None or not _at.dtype_ladder_armed():
            return None
        return _at.variant_choice("dtype_ladder")

    def loss_of(param_dict, x, y, key, fp8=None):
        cdt = compute_dtype
        arm = _ladder_arm()
        if arm == "bf16":
            # the bf16 dtype-ladder arm (round 14).  Consulted at
            # TRACE time only, and only when the knob arms it (a
            # dtype change is not numerics-neutral).
            cdt = "bfloat16"
        if arm == "fp8" and fp8 is not None:
            # the fp8 rung (round 19): matmul/conv weights and the
            # batch input snap to the e4m3 grid at the delayed
            # per-tensor scales carried in opt_state['_fp8']; the
            # straight-through backward snaps their gradients to e5m2
            # (ops/pallas_opt.fp8_qdq).  Norm params (amp policy) and
            # every other op stay in fp32 — the matmul/conv-only
            # eligibility the contrib/amp FP8 lists mirror.  A cached
            # fp8 winner reaching a step whose build did not provision
            # the state (fp8 is None) falls through to fp32: never
            # take a rung the build did not provision for.
            gscale = fp8["g"][0]
            param_dict = {
                n: (_po.fp8_qdq(v, fp8["w"][n][0], gscale)
                    if n in fp8["w"] else v)
                for n, v in param_dict.items()}
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = _po.fp8_qdq(x, fp8["x"][0], gscale)
        if cdt is not None:
            # AMP policy (reference contrib/amp list semantics): matmul/
            # conv weights in bf16, norm affine+stats in fp32
            param_dict = amp_cast_params(param_dict, cdt)
            x = x.astype(cdt)
        out = apply_fn(param_dict, x, key=key)
        loss_nd = loss_fn(nd.NDArray(out.astype(jnp.float32)),
                          nd.NDArray(y))
        return jnp.mean(loss_nd._data)

    dynamic_scaling = loss_scale == "dynamic"
    static_scale = float(loss_scale) if (
        loss_scale is not None and not dynamic_scaling) else 1.0

    # ---- sharded-server mode resolution (parallel.zero) --------------
    from . import zero as _zero

    ps_mode = optimizer_sharding
    env_ps = _zero.resolve_sharding_env()
    if env_ps is False:
        ps_mode = None  # '0' force-disables even explicit opt-ins
    elif ps_mode is None and env_ps == "ps":
        ps_mode = "ps"
    if ps_mode not in (None, False, "", "ps"):
        raise MXNetError(
            f"unknown optimizer_sharding {ps_mode!r} (only 'ps')")
    ps_mode = "ps" if ps_mode == "ps" else None
    # ---- ZeRO stage resolution (env overrides the argument, same
    # precedence as MXNET_OPTIMIZER_SHARDING; a stage implies the
    # sharded exchange unless the env force-off already vetoed it)
    env_stage = _zero.resolve_zero_stage()
    stage = env_stage if env_stage is not None else zero_stage
    if stage not in (None, 1, 2, 3):
        raise MXNetError(
            f"unknown zero_stage {stage!r} (use 1, 2 or 3)")
    if stage is not None and ps_mode is None and env_ps is not False:
        ps_mode = "ps"
    if ps_mode and mesh is None:
        import warnings

        warnings.warn(
            "optimizer_sharding='ps' needs a mesh (nothing to shard "
            "over on one device) — step stays replicated", stacklevel=2)
        ps_mode = None
    if ps_mode and param_spec:
        raise MXNetError(
            "optimizer_sharding='ps' does not compose with param_spec "
            "(tensor parallelism) yet")
    if gradient_compression is not None and not ps_mode:
        raise MXNetError(
            "gradient_compression in make_train_step requires "
            "optimizer_sharding='ps' (the replicated step has no "
            "bucketed wire to compress)")

    names = list(params)
    comp_threshold = None
    if not ps_mode:
        stage = None
    elif stage is None:
        stage = 2  # the default exchange: reduce-scattered gradients
    if ps_mode:
        n_sh = int(mesh.shape[data_axis])
        _zero.check_bucket_rule(opt)
        plan = _zero.plan_buckets(params, n_sh, capacity=bucket_bound)
        bucket_keys = _zero.stage3_param_keys(plan)
        # optimizer state is created over the FLAT buckets and lives
        # sharded for the step's whole life (the server owning its key
        # shard's state) — per-chip state bytes ~ total/N
        opt_state = {
            bk: opt.fused_state(_zero.flatten_bucket(b, params))
            for bk, b in zip(bucket_keys, plan)
        }
        if gradient_compression is not None:
            ctype = gradient_compression.get("type", "2bit")
            if ctype != "2bit":
                raise MXNetError(f"unsupported compression {ctype}")
            comp_threshold = float(
                gradient_compression.get("threshold", 0.5))
            for i, b in enumerate(plan):
                # error-feedback residual: per bucket-SHARD, fp32 (the
                # narrow-accumulate discipline — a bf16 residual would
                # lose the feedback below threshold/256)
                opt_state[f"_residual{i}"] = jnp.zeros((b.padded,),
                                                       jnp.float32)
        if stage == 3:
            # stage 3: the params move into their persistent layout —
            # one flat padded bucket per plan entry, sharded over the
            # data axis at jit wiring below (per-chip param bytes
            # ~ total/N); the named tree only ever rematerializes
            # transiently inside the step's per-bucket gathers
            params = {bk: _zero.flatten_bucket(b, params)
                      for bk, b in zip(bucket_keys, plan)}
    else:
        opt_state = {n: opt.fused_state(v) for n, v in params.items()}
    if dynamic_scaling:
        opt_state["_loss_scale"] = (
            jnp.float32(2.0 ** 16),  # initial scale (reference amp)
            jnp.zeros((), jnp.int32),  # consecutive-finite counter
        )
    if nan_guard is None:
        from ..config import get_env

        nan_guard = get_env("MXNET_BAD_STEP_LIMIT") > 0
    nan_guard = bool(nan_guard) and not dynamic_scaling
    if nan_guard:
        opt_state["_bad_steps"] = jnp.zeros((), jnp.int32)

    # ---- fp8 dtype-ladder rung (round 19): delayed-scaling state.
    # Provisioned at BUILD time whenever the armed roster names fp8
    # (the race's fp8 arm and a cached fp8 winner both need it in the
    # SAME opt_state pytree the other arms thread through), absent
    # otherwise — an unarmed build's program stays HLO bit-identical
    # to round 18.  Per-tensor scales: one (scale, amax-history) pair
    # per matmul/conv weight, one for the batch input, one e5m2 pair
    # for the gradients; history length is MXNET_FP8_AMAX_HISTORY.
    # Not yet composed with the sharded-server exchange (gradients
    # live there as flat bucket shards, not named tensors).
    from ..ops import pallas_opt as _po

    fp8_rung = (compute_dtype is None and _at.dtype_ladder_armed()
                and "fp8" in _at.ladder_rungs() and not ps_mode)
    if fp8_rung:
        from ..config import get_env

        fp8_hist_len = max(1, int(get_env("MXNET_FP8_AMAX_HISTORY")))

        def _fp8_pair():
            return (jnp.float32(1.0),  # step-1 scale: identity until
                    #                     the history holds a real amax
                    jnp.zeros((fp8_hist_len,), jnp.float32))

        fp8_weight_names = [
            n for n in names
            if not _is_norm_stat(n) and getattr(params[n], "ndim", 0) >= 2
        ]
        opt_state["_fp8"] = {
            "x": _fp8_pair(),
            "g": _fp8_pair(),
            "w": {n: _fp8_pair() for n in fp8_weight_names},
        }

    def _fp8_bookkeeping(fp8_state, params_, x, grads):
        """The in-graph delayed-scaling update (ops/pallas_opt.
        fp8_delayed_scale beside the loss-scale bookkeeping): observe
        each quantized tensor class's |t|_inf THIS step, roll it into
        the history, and derive the NEXT step's scale — no host sync,
        and an overflowed observation backs the scale off without
        corrupting the state."""
        new = {}
        _, xh = fp8_state["x"]
        if jnp.issubdtype(x.dtype, jnp.floating):
            x_amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
        else:
            x_amax = jnp.max(xh)  # integer inputs never quantize
        nh, ns = _po.fp8_delayed_scale(xh, x_amax)
        new["x"] = (ns, nh)
        _, gh = fp8_state["g"]
        g_amax = jnp.float32(0.0)
        for n in fp8_state["w"]:
            g_amax = jnp.maximum(
                g_amax, jnp.max(jnp.abs(grads[n].astype(jnp.float32))))
        ngh, ngs = _po.fp8_delayed_scale(gh, g_amax,
                                         fmax=_po.E5M2_MAX)
        new["g"] = (ngs, ngh)
        new_w = {}
        for n, (_, wh) in fp8_state["w"].items():
            w_amax = jnp.max(jnp.abs(params_[n].astype(jnp.float32)))
            nwh, nws = _po.fp8_delayed_scale(wh, w_amax)
            new_w[n] = (nws, nwh)
        new["w"] = new_w
        return new

    # ---- in-graph numerics monitor (telemetry.numerics, Monitor 2.0):
    # per-gradient summary reductions compile INTO the step and ride in
    # the returned state under the reserved _numerics key — zero host
    # callbacks, zero sync; the telemetry wrapper below reads them back
    # only on sampled steps.  Unarmed = the traced program is
    # bit-identical to a build without the monitor.
    from ..telemetry import numerics as _nm

    numerics_on = _nm.armed()
    if numerics_on and ps_mode:
        import warnings

        warnings.warn(
            "MXNET_NUMERICS under optimizer_sharding='ps' is not "
            "supported yet (gradients live as scattered bucket "
            "shards, not named tensors) — monitor disabled for this "
            "step", stacklevel=2)
        numerics_on = False
    if numerics_on:
        opt_state["_numerics"] = _nm.summary_template(
            dict.fromkeys([*names, "__loss"]))

    def _nm_pack(grads, loss):
        stats = _nm.summarize_tree(grads)
        stats["__loss"] = _nm.summary(loss)
        return stats

    # the dynamic-loss-scale verdict lives in ops/pallas_opt beside the
    # fp8 delayed-scaling verdict (round 19) — one module, so the two
    # backoff rules cannot drift; the replicated and sharded arms both
    # call this ONE copy (sharded-vs-replicated parity contract)
    _scale_bookkeeping = _po.scale_bookkeeping

    def _apply_updates(params_, opt_state_, grads, t, key):
        new_p, new_s = {}, {}
        for i, n in enumerate(names):
            # stochastic rules (SGLD) get a distinct per-param key;
            # deterministic ones skip the fold-in (it compiles to ~2
            # dead scalar ops per parameter otherwise)
            sub = jax.random.fold_in(key, i) if opt.needs_key else None
            new_p[n], new_s[n] = opt.fused_update(
                params_[n], grads[n], opt_state_[n], t, key=sub)
        return new_p, new_s

    def step(params_, opt_state_, x, y, key, t):
        # fp8 rung wiring (trace-time): thread the delayed scales into
        # the loss, and roll this step's amax observations into the
        # history.  On the other arms (a race's fp32/bf16 force, or a
        # non-fp8 winner) the provisioned state passes through
        # untouched so every arm emits the same opt_state pytree.
        fp8_on = fp8_rung and _ladder_arm() == "fp8"
        fp8_state = opt_state_["_fp8"] if fp8_rung else None

        def lo(p, x_, y_, k_):
            return loss_of(p, x_, y_, k_,
                           fp8=fp8_state if fp8_on else None)

        def _fp8_carry(new_s, grads):
            if fp8_rung:
                new_s["_fp8"] = _fp8_bookkeeping(
                    fp8_state, params_, x, grads) if fp8_on \
                    else fp8_state
            return new_s

        if dynamic_scaling:
            scale, good = opt_state_["_loss_scale"]

            def scaled_loss(p, x_, y_, k_):
                return lo(p, x_, y_, k_) * scale

            sloss, sgrads = jax.value_and_grad(scaled_loss)(
                params_, x, y, key)
            inv = 1.0 / scale
            grads = jax.tree_util.tree_map(lambda g: g * inv, sgrads)
            finite = jnp.array(True)
            for g in jax.tree_util.tree_leaves(grads):
                finite = finite & jnp.isfinite(g).all()
            up_p, up_s = _apply_updates(
                {n: params_[n] for n in names},
                {n: opt_state_[n] for n in names}, grads, t, key)
            # overflow: skip the update, halve the scale; after 2000
            # consecutive finite steps, double it (reference amp scaler)
            new_p = {n: jnp.where(finite, up_p[n], params_[n])
                     for n in names}
            new_s = {
                n: jax.tree_util.tree_map(
                    lambda u, o: jnp.where(finite, u, o),
                    up_s[n], opt_state_[n])
                for n in names
            }
            new_s["_loss_scale"] = _scale_bookkeeping(finite, scale,
                                                      good)
            # the fp8 histories update even on a skipped step — the
            # overflow observation is exactly what backs the scale off
            new_s = _fp8_carry(new_s, grads)
            if numerics_on:
                new_s["_numerics"] = _nm_pack(grads, sloss / scale)
            # unscale with the scale the loss was COMPUTED with, not the
            # adjusted one, or the reported loss jumps 2x on every
            # scale-change step
            return sloss / scale, new_p, new_s

        if static_scale != 1.0:
            def scaled_loss(p, x_, y_, k_):
                return lo(p, x_, y_, k_) * static_scale

            loss, grads = jax.value_and_grad(scaled_loss)(params_, x, y,
                                                          key)
            loss = loss / static_scale
            grads = jax.tree_util.tree_map(
                lambda g: g / static_scale, grads)
        else:
            loss, grads = jax.value_and_grad(lo)(params_, x, y, key)
        if nan_guard:
            # skip-and-count: a non-finite step leaves params/opt state
            # untouched and bumps the consecutive-bad counter; any
            # finite step resets it (MXNET_BAD_STEP_LIMIT policy is
            # enforced by the host reading _bad_steps)
            finite = jnp.isfinite(loss)
            for g in jax.tree_util.tree_leaves(grads):
                finite = finite & jnp.isfinite(g).all()
            up_p, up_s = _apply_updates(
                params_, {n: opt_state_[n] for n in names}, grads, t,
                key)
            new_p = {n: jnp.where(finite, up_p[n], params_[n])
                     for n in names}
            new_s = {
                n: jax.tree_util.tree_map(
                    lambda u, o: jnp.where(finite, u, o),
                    up_s[n], opt_state_[n])
                for n in names
            }
            new_s["_bad_steps"] = jnp.where(
                finite, jnp.int32(0), opt_state_["_bad_steps"] + 1)
            new_s = _fp8_carry(new_s, grads)
            if numerics_on:
                # stats of the step AS IT HAPPENED, guard or no guard:
                # the bad step's NaN counts are the explanation
                new_s["_numerics"] = _nm_pack(grads, loss)
            return loss, new_p, new_s
        new_p, new_s = _apply_updates(
            params_, {n: opt_state_[n] for n in names}, grads, t, key)
        new_s = _fp8_carry(new_s, grads)
        if numerics_on:
            new_s["_numerics"] = _nm_pack(grads, loss)
        return loss, new_p, new_s

    # ---- sharded-server step (optimizer_sharding="ps") ---------------
    if ps_mode:
        needs_seg = not getattr(opt, "fused_elementwise", True)
        seg_info = [_zero.bucket_segments(b) for b in plan] \
            if needs_seg else None
        check_finite = dynamic_scaling or nan_guard
        # the fused_bucket_opt lowering, resolved at BUILD time under
        # the shared flat-layout key (zero.resolve_bucket_variant) so
        # a winner measured by the Module updater's race — or a bench
        # bucket race over the same plan — reaches this step too; None
        # (undecided) leaves the trace-time variant_choice consult in
        # charge, so force scopes and program-scope winners still work
        ps_pallas = _zero.resolve_bucket_variant(opt, plan, mesh, stage)

        def ps_local_step(params_, opt_state_, x, y, key, t):
            # runs PER DEVICE under shard_map: params replicated in
            # (stages 1/2) or the locally-owned flat bucket shards
            # (stage 3), x/y are the local batch shard, bucket
            # states/residuals are the locally-owned shard
            idx = jax.lax.axis_index(data_axis)
            fkey = jax.random.fold_in(key, idx)
            if dynamic_scaling:
                scale, good = opt_state_["_loss_scale"]
            else:
                scale = static_scale

            def local_loss(p, x_, y_, k_):
                if stage == 3:
                    # bucket-wise all-gather PREFETCH: every bucket's
                    # gather is issued with no inter-bucket data
                    # dependency, so the scheduler runs bucket k+1's
                    # gather while the compute consuming bucket k
                    # executes instead of serializing all gathers at
                    # the step head
                    named = {}
                    for bk_, b_ in zip(bucket_keys, plan):
                        named.update(_zero.unflatten_bucket(
                            b_, jax.lax.all_gather(
                                p[bk_], data_axis, tiled=True)))
                    p = named
                lv = loss_of(p, x_, y_, k_)
                if dynamic_scaling or static_scale != 1.0:
                    lv = lv * scale
                return lv

            lval, lgrads = jax.value_and_grad(local_loss)(
                params_, x, y, fkey)
            # grad of the GLOBAL mean loss = psum(local-mean grads)/N;
            # the unscale folds into the same multiply
            inv = 1.0 / n_sh
            if dynamic_scaling:
                inv = inv / scale
            elif static_scale != 1.0:
                inv = inv / static_scale
            # parity with the replicated arms: dynamic scaling's
            # verdict is GRADIENT finiteness only (a scaled loss can
            # overflow while the unscaled grads are fine); the nan
            # guard additionally checks the loss, as replicated does
            finite = None
            if nan_guard:
                finite = jnp.isfinite(lval)
            elif dynamic_scaling:
                finite = jnp.array(True)
            staged = []
            for i, (bk, b) in enumerate(zip(bucket_keys, plan)):
                w_sh_in = None
                if stage == 3:
                    # differentiating through the tiled all-gather IS
                    # the exchange: its transpose emitted one reduce-
                    # scatter per bucket, interleaved with the rest of
                    # the backward compute — the gradient arrives
                    # already summed and scattered to the owned shard
                    g_sh = lgrads[bk]
                    w_sh_in = params_[bk]
                elif stage == 1:
                    # classic ZeRO-1 for the stage ladder: the whole
                    # reduced bucket lands on every device (one
                    # all-reduce) and the owned shard is sliced off it
                    g_sh = _zero.shard_slice(
                        jax.lax.psum(_zero.flatten_bucket(b, lgrads),
                                     data_axis), n_sh, idx)
                else:
                    # THE stage-2 exchange: one reduce-scatter for the
                    # whole bucket replaces len(b.names) per-tensor
                    # all-reduces
                    g_sh = jax.lax.psum_scatter(
                        _zero.flatten_bucket(b, lgrads), data_axis,
                        scatter_dimension=0, tiled=True)
                g32 = g_sh.astype(jnp.float32) * inv
                new_resid = None
                if comp_threshold is not None:
                    from ..kvstore import quantize_2bit

                    # compression: the finiteness verdict stays a
                    # separate jnp check on the PRE-quantize gradient
                    # (the kernel's fused verdict would see the
                    # quantized values)
                    if check_finite:
                        finite = finite & jnp.isfinite(g32).all()
                    acc = g32 + opt_state_[f"_residual{i}"]
                    g32, new_resid = quantize_2bit(acc, comp_threshold)
                sub = jax.random.fold_in(
                    jax.random.fold_in(key, i), idx) \
                    if opt.needs_key else None
                # bucket_shard_update casts g to the bucket dtype and
                # runs the jnp rule OR the fused Pallas kernel per the
                # "fused_bucket_opt" variant decision; on the kernel
                # arm the loss-scale finiteness verdict of the RAW f32
                # gradient rides the same VMEM pass (want_finite)
                want_fin = check_finite and comp_threshold is None
                res = _zero.bucket_shard_update(
                    b, opt, params_, g32, opt_state_[bk], t,
                    n_shards=n_sh, idx=idx, axis=data_axis,
                    seg=seg_info[i] if needs_seg else None, key=sub,
                    pallas=ps_pallas, want_finite=want_fin,
                    w_sh=w_sh_in)
                if want_fin:
                    w_sh, uw, us, bfin = res
                    # finiteness verdict on the SCATTERED shard (each
                    # device sees params/N elements; psum below makes
                    # the verdict global) — fused when the kernel ran,
                    # bit-identical jnp check otherwise
                    finite = finite & (
                        bfin if bfin is not None
                        else jnp.isfinite(g32).all())
                else:
                    w_sh, uw, us = res
                staged.append((i, bk, b, w_sh, uw, us, new_resid))
            new_p, new_s = {}, {}
            if check_finite:
                bad = jax.lax.psum(1 - finite.astype(jnp.int32),
                                   data_axis)
                finite = bad == 0
            for i, bk, b, w_sh, uw, us, new_resid in staged:
                if check_finite:
                    # skip-the-update selection (dynamic scaling / nan
                    # guard): shard, state AND residual all hold
                    uw = jnp.where(finite, uw, w_sh)
                    us = jax.tree_util.tree_map(
                        lambda u, o: jnp.where(finite, u, o), us,
                        opt_state_[bk])
                    if new_resid is not None:
                        new_resid = jnp.where(
                            finite, new_resid,
                            opt_state_[f"_residual{i}"])
                new_s[bk] = us
                if new_resid is not None:
                    new_s[f"_residual{i}"] = new_resid
                if stage == 3:
                    # params stay sharded: the updated shard IS the
                    # new param bucket — no gather-back (the next
                    # forward's prefetch gathers it)
                    new_p[bk] = uw
                else:
                    new_p.update(_zero.gather_bucket(b, uw, data_axis))
            loss = jax.lax.pmean(lval, data_axis)
            if dynamic_scaling:
                new_s["_loss_scale"] = _scale_bookkeeping(finite, scale,
                                                          good)
                loss = loss / scale
            elif static_scale != 1.0:
                loss = loss / static_scale
            if nan_guard:
                new_s["_bad_steps"] = jnp.where(
                    finite, jnp.int32(0), opt_state_["_bad_steps"] + 1)
            return loss, new_p, new_s

        if stage == 3:
            ps_p_specs = {bk: P(data_axis) for bk in bucket_keys}
        else:
            ps_p_specs = {n: P() for n in params}
        ps_s_specs = jax.tree_util.tree_map(
            lambda l: P(data_axis) if getattr(l, "ndim", 0) else P(),
            opt_state)
        step = compat_shard_map(
            ps_local_step, mesh,
            in_specs=(ps_p_specs, ps_s_specs, P(data_axis),
                      P(data_axis), P(), P()),
            out_specs=(P(), ps_p_specs, ps_s_specs))

    # ---- in-step variant autotuning (mxnet_tpu.autotune) -------------
    mesh_d = _at.mesh_desc(mesh)
    try:
        plat = jax.local_devices()[0].platform
    except Exception:
        plat = None
    _tune_level = None if autotune is None else int(autotune)
    if sample_data is not None and _at.enabled(_tune_level):
        if mesh is None:
            xs, ys = sample_data
            _at.tune_train_step(
                step, params, opt_state, jnp.asarray(xs),
                jnp.asarray(ys), jax.random.key(0),
                variant_ops=variant_ops, platform=plat, mesh=mesh_d,
                level=_tune_level)
        else:
            # in-step timing under a mesh needs sharded sample state
            # (not built yet at this point) — be loud, not silent:
            # cached winners recorded for this mesh key still apply
            import warnings

            warnings.warn(
                "make_train_step: in-step autotuning under a mesh is "
                "not yet supported; sample_data ignored (cached "
                "winners for this mesh key still apply)", stacklevel=2)

    def _scoped_step(params_, opt_state_, x, y, key, t):
        # cached winners for this program signature apply at TRACE time
        # (the scope is entered on every call; only the first traces);
        # autotune=False opts this step out entirely
        if not _at.enabled(_tune_level):
            return step(params_, opt_state_, x, y, key, t)
        with _at.program_scope(x.shape, x.dtype, platform=plat,
                               mesh=mesh_d):
            return step(params_, opt_state_, x, y, key, t)

    donate_argnums = (0, 1) if donate else ()
    if donate:
        # device_put of an already-committed array aliases it, so the
        # first donated step would delete the gluon block's own weight
        # buffers out from under it.  A jitted identity materializes
        # fresh buffers the step is then free to consume.
        params = jax.jit(lambda p: p)(params)
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        batch_sharding = NamedSharding(mesh, P(data_axis))
        if ps_mode:
            # params replicate (stages 1/2) or live sharded as flat
            # buckets (stage 3 — the parameter-memory win); bucket
            # states + residuals live SHARDED over the data axis (the
            # ZeRO-1 memory win); scalar entries (loss-scale, bad-step
            # counters) replicate
            shard1 = NamedSharding(mesh, P(data_axis))
            p_shard = jax.tree_util.tree_map(
                lambda _: shard1 if stage == 3 else repl, params)
            opt_shard = jax.tree_util.tree_map(
                lambda l: shard1 if getattr(l, "ndim", 0) else repl,
                opt_state)
        elif param_spec is None:
            p_shard = jax.tree_util.tree_map(lambda _: repl, params)
            opt_shard = jax.tree_util.tree_map(lambda _: repl, opt_state)
        else:
            p_shard = {
                n: NamedSharding(mesh, param_spec.get(n, P()))
                for n in params
            }
            # optimizer state (per-param moments) shards like its param;
            # scalar entries (loss-scale state) replicate
            opt_shard = {
                n: jax.tree_util.tree_map(
                    lambda s, sh=p_shard.get(n, repl): sh
                    if getattr(s, "ndim", 0) else repl, opt_state[n])
                for n in opt_state
            }
        step_fn = jax.jit(
            _scoped_step,
            in_shardings=(p_shard, opt_shard, batch_sharding,
                          batch_sharding, None, None),
            out_shardings=(None, p_shard, opt_shard),
            donate_argnums=donate_argnums,
        )
        params = jax.device_put(params, p_shard)
        opt_state = jax.device_put(opt_state, opt_shard)
    else:
        step_fn = jax.jit(_scoped_step, donate_argnums=donate_argnums,
                          static_argnums=())

    # ---- telemetry: compile events + program introspection -----------
    # One host-side record per (re)trace of the fused step: the RunLog
    # diffs the fingerprint against the previous one for this program
    # to name the retrace cause (shape / dtype / autotune_winner /
    # hyper_params / sharding).  A signature seen before that recurs
    # after a change is a cache "hit" (XLA's jit cache still holds it).
    # MXNET_RUNLOG unset => current() is None => zero per-step work
    # beyond one call + dict lookup.
    from .. import telemetry as _tm

    _jitted_step = step_fn
    _tm_hyper = {k: v for k, v in sorted(vars(opt).items())
                 if not k.startswith("_")
                 and isinstance(v, (int, float, bool, str, type(None)))}
    # stage 2 keeps the historic "ps" stamp (it IS that program);
    # stages 1/3 trace different exchanges and must name themselves so
    # the RunLog can blame a retrace on a stage flip
    _tm_sharding = "none" if not ps_mode else (
        "ps" if stage == 2 else f"zero{stage}")
    _tm_seen = set()
    _tm_last = [None]
    _nm_period = _nm.sample_period() if numerics_on else 0
    _nm_step = [0]

    def step_fn(p, o, x, y, key, t, _inner=_jitted_step):
        rl = _tm.current()
        if rl is not None:
            sig = (tuple(x.shape), str(x.dtype))
            if sig not in _tm_seen or sig != _tm_last[0]:
                cache = "hit" if sig in _tm_seen else "miss"
                winners = {}
                if _at.enabled(_tune_level):
                    winners = {
                        op: _at.lookup(op, x.shape, x.dtype,
                                       platform=plat, mesh=mesh_d)
                        for op in variant_ops}
                try:
                    rl.compile_event(
                        "train_step",
                        _tm.compile_fingerprint(
                            sig[0], sig[1], True, winners=winners,
                            hyper=_tm_hyper, sharding=_tm_sharding),
                        cache=cache)
                    if cache == "miss":
                        # memory/flop/collective introspection of the
                        # program about to run — a persistent-cache
                        # disk hit when the XLA cache is enabled
                        _tm.describe_program(_inner, p, o, x, y, key,
                                             t, program="train_step")
                except Exception:
                    pass  # telemetry must never kill the step
                _tm_seen.add(sig)
                _tm_last[0] = sig
        result = _inner(p, o, x, y, key, t)
        if numerics_on and rl is not None:
            # sampled readback of the in-graph summaries: the ONLY
            # steps that pay a device sync for the monitor.  Inside an
            # outer trace (bench's chained fori_loop) the values are
            # tracers — nothing to read, skip.
            try:
                loss_v, _, new_s = result
                vecs = new_s.get("_numerics")
                if vecs is not None and not isinstance(
                        loss_v, jax.core.Tracer):
                    i = _nm_step[0]
                    _nm_step[0] = i + 1
                    if i % _nm_period == 0:
                        _nm.emit(rl, i, vecs, where="grad")
            except Exception:
                pass  # the monitor must never kill the step
        return result

    from ..resilience import faultsim

    if faultsim.armed("step.loss_nan"):
        # fault harness only (MXNET_FAULT_SPEC names the point): armed
        # hits poison the batch with NaN BEFORE the compiled step, so
        # the in-graph guard sees a genuinely non-finite step; the
        # disarmed fast path never grows this wrapper
        inner_step = step_fn

        def step_fn(p, o, x, y, key, t, _inner=inner_step):
            if faultsim.inject("step.loss_nan") == "nan":
                # integer dtypes have no NaN — poisoning them is a
                # silent no-op, so pick the first inexact input (token
                # id models poison through their float labels)
                x, y = jnp.asarray(x), jnp.asarray(y)
                if jnp.issubdtype(x.dtype, jnp.inexact):
                    x = x * jnp.asarray(jnp.nan, x.dtype)
                elif jnp.issubdtype(y.dtype, jnp.inexact):
                    y = y * jnp.asarray(jnp.nan, y.dtype)
                else:
                    import warnings

                    warnings.warn(
                        "step.loss_nan injection skipped: neither x "
                        "nor y has an inexact dtype to poison",
                        stacklevel=2)
            return _inner(p, o, x, y, key, t)

    if step_fn is not _jitted_step:
        # the telemetry/fault wrappers are plain functions; callers
        # introspecting the program (bench.py, the multichip dryrun)
        # still need jit's lower() — same XLA program either way
        step_fn.lower = _jitted_step.lower
    if ps_mode:
        # the layout contract for checkpointing/eval callers: under
        # stage 3 the params pytree is flat buckets, and
        # zero.gather_stage3_params(step_fn.zero_plan, params)
        # reassembles the named tree
        step_fn.zero_stage = stage
        step_fn.zero_plan = plan

    return step_fn, params, opt_state


class DataParallelTrainer:
    """High-level fused data-parallel training driver.

    The TPU-native replacement for Module+DataParallelExecutorGroup+
    KVStore: one object owning the sharded params/opt state and a
    compiled SPMD step.  Call ``fit_batch(x, y)`` per batch;
    ``sync_to_block()`` writes weights back into the Gluon block for
    checkpointing/eval via the normal APIs.
    """

    def __init__(self, block, loss_fn, optimizer="sgd", mesh=None,
                 **opt_kwargs):
        self._block = block
        self._mesh = mesh
        self._step_fn, self._params, self._opt_state = make_train_step(
            block, loss_fn, optimizer=optimizer, mesh=mesh, **opt_kwargs)
        self._t = 0
        self._key = jax.random.key(0)

    def fit_batch(self, x, y):
        x = x._data if isinstance(x, nd.NDArray) else jnp.asarray(x)
        y = y._data if isinstance(y, nd.NDArray) else jnp.asarray(y)
        self._t += 1
        self._key, sub = jax.random.split(self._key)
        loss, self._params, self._opt_state = self._step_fn(
            self._params, self._opt_state, x, y, sub, float(self._t))
        return loss

    @property
    def params(self):
        return self._params

    def sync_to_block(self):
        from ..gluon.block import _collect_all_params

        params = self._params
        if getattr(self._step_fn, "zero_stage", None) == 3:
            # stage-3 params live as flat bucket shards: reassemble
            # the named tree (host_gather handles the multi-process
            # world where no single host holds a whole bucket)
            from ..resilience.elastic import host_gather

            params = zero.gather_stage3_params(
                self._step_fn.zero_plan,
                {k: host_gather(v) for k, v in params.items()})
        for p in _collect_all_params(self._block):
            if p.name in params:
                # gather off the mesh so eager single-device ops work
                v = jnp.asarray(onp.asarray(params[p.name]))
                p.data()._adopt(v)


from . import moe, pipeline, ring, zero  # noqa: E402  (submodule
#                                           re-exports)
from .predict import make_predict_fn, tune_microbatch  # noqa: E402
