"""Inference predictor with bind-time micro-batch autotuning.

Reference analog: ``cudnn_tune='fastest'`` (src/operator/nn/cudnn/
cudnn_algoreg-inl.h) benchmarks candidate convolution algorithms at
bind time and caches the winner per shape.  On TPU the algorithm space
is XLA's conv-emitter selection, which is keyed to the operand shapes —
and its cost model picks badly for some large-batch fp32 shapes
(measured r05, v5e: ResNet-152 fp32 bs128 runs 1.5x slower PER IMAGE
than bs32; the same net as ``lax.map`` over 4 chunks of 32 runs 58%
faster than the monolithic batch and matches bs32's per-image cost).
The tunable knob is therefore the micro-batch split: run a batch-B
forward as ``lax.map`` over k chunks of B/k inside ONE jitted program,
picking k by measuring, exactly like cudnn_tune picks an algo.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp

__all__ = ["make_predict_fn", "tune_microbatch"]


#: chunk counts up to this unroll by default; beyond it the k-times
#: program-size growth starts to cost more compile time than the loop
#: machinery costs run time
_UNROLL_LIMIT = 8


def make_predict_fn(apply_fn, *, microbatch=1, unroll="auto"):
    """Jitted ``predict(params, x)`` that runs ``apply_fn(params, xc)``
    over ``microbatch`` sequential chunks of the leading batch axis,
    reassembling each output pytree leaf.  microbatch=1 is the plain
    full-batch program.

    unroll=True inlines the k chunk programs: each chunk compiles
    exactly like a standalone batch-B/k call, so XLA keeps its
    double-buffered schedule per chunk.  unroll=False uses ``lax.map``
    (one compiled chunk body, small program) — measured r05/r06 on
    v5e, the map body LOSES cross-iteration double-buffering and ran
    bs128-as-4x32 ~22% slower per image than four standalone bs32
    calls (12.96 ms vs 4x2.65 ms), which re-opened the fp32
    batch-scaling regression the microbatch split exists to fix.
    The "auto" default therefore unrolls for k <= 8 and falls back to
    ``lax.map`` only for chunk counts where the unrolled program size
    would dominate compile time."""
    from ..config import setup_compilation_cache

    setup_compilation_cache()
    k = int(microbatch)
    if unroll == "auto":
        unroll = k <= _UNROLL_LIMIT

    @jax.jit
    def predict(params, x):
        if k == 1:
            return apply_fn(params, x)
        b = x.shape[0]
        if b % k:
            raise ValueError(f"batch {b} not divisible by microbatch {k}")
        xc = x.reshape((k, b // k) + x.shape[1:])
        if unroll:
            chunks = [apply_fn(params, xc[i]) for i in range(k)]
            return jax.tree_util.tree_map(
                lambda *os: jnp.concatenate(os, axis=0), *chunks)
        out = jax.lax.map(lambda c: apply_fn(params, c), xc)
        return jax.tree_util.tree_map(
            lambda o: o.reshape((b,) + o.shape[2:]), out)

    return predict


def _chain_time(fn, args, iters=30):
    """Marginal seconds/call via a fori_loop-chained device program —
    the same two-K-slope method as benchmark/devtime.py, trimmed for
    in-package use (host timing alone is unreliable on tunneled TPUs:
    dispatch jitter can exceed small-batch inference latency)."""

    def zero_of(out):
        leaves = jax.tree_util.tree_leaves(out)
        z = jnp.float32(0.0)
        for o in leaves:
            if jnp.issubdtype(o.dtype, jnp.floating):
                z = z + jnp.sum(o.astype(jnp.float32))
        z = jnp.where(jnp.isfinite(z), z, 0.0)
        return jnp.minimum(jnp.abs(z), 0.0)

    @jax.jit
    def loop(n, a):
        def body(_, carry):
            cargs, s = carry
            cargs = list(cargs)
            cargs[0] = cargs[0] + s.astype(cargs[0].dtype)
            cargs = jax.lax.optimization_barrier(tuple(cargs))
            return cargs, zero_of(fn(*cargs))

        _, s = jax.lax.fori_loop(0, n, body,
                                 (tuple(a), jnp.float32(0.0)))
        return s

    def run(n):
        t0 = time.perf_counter()
        _ = float(loop(jnp.int32(n), args))
        return time.perf_counter() - t0

    run(2)  # compile
    t1 = run(2)
    t2 = run(2 + iters)
    return max(t2 - t1, 1e-9) / iters


def _enc_form(k, unroll):
    return f"{k}:{'unroll' if unroll else 'map'}"


def tune_microbatch(apply_fn, params, sample_x, candidates=(1, 2, 4),
                    iters=20, try_unroll=True, use_cache=None):
    """Measure ``apply_fn`` under each micro-batch split (and, for
    k>1, both the lax.map and unrolled chunk forms) on the sample batch
    and return (best, results) where best = (k, unroll) and results
    maps (k, unroll) -> seconds.  Candidates that do not divide the
    batch are skipped.  Bind-time cost is a few timed loops per
    candidate — the cudnn_tune='fastest' contract.

    Winners persist through the framework autotune cache
    (mxnet_tpu.autotune, keyed on a params-signature digest + the
    sample batch shape/dtype/platform): a later call — or another
    process — with the same model/input signature reloads the recorded
    winner and timings instead of re-timing.  use_cache=None follows
    MXNET_AUTOTUNE (level 2 re-times even on a hit); use_cache=False
    bypasses."""
    import hashlib

    from .. import autotune as at

    b = sample_x.shape[0]
    candidates = tuple(candidates)
    if not any(k >= 1 and b % k == 0 for k in candidates):
        candidates = candidates + (1,)  # always have a valid baseline
    # the model rides in the key via its parameter signature (leaf
    # shapes+dtypes), so two different nets sharing an input shape
    # cannot inherit each other's winner — the same discrimination the
    # cudnn algo registry gets from keying on the filter descriptor
    import jax

    sig = ",".join(
        f"{tuple(getattr(l, 'shape', ()))}{getattr(l, 'dtype', '')}"
        for l in jax.tree_util.tree_leaves(params))
    op_key = ("predict_microbatch:"
              + hashlib.sha1(sig.encode()).hexdigest()[:12])
    lvl = at.autotune_level() if use_cache is None else \
        int(bool(use_cache))
    if lvl == 1:
        entry = at.lookup_entry(op_key, sample_x.shape,
                                sample_x.dtype)
        # a corrupt/partially-written autotune.json must mean
        # "re-tune", never a crash: the loader already drops non-dict
        # entries, and any malformed winner/timings payload inside a
        # surviving entry falls through to the measuring path below
        # (whose record() rewrites the file atomically)
        try:
            w = entry.get("winner") if entry else None
        except AttributeError:
            w = None
        if w is not None:
            if isinstance(w, (list, tuple)) and len(w) == 2 \
                    and w[0] in candidates and b % int(w[0]) == 0:
                results = {}
                try:
                    for ks, t in (entry.get("timings") or {}).items():
                        kk, form = str(ks).split(":")
                        results[(int(kk), form == "unroll")] = float(t)
                except (AttributeError, TypeError, ValueError):
                    results = {}
                best = (int(w[0]), bool(w[1]))
                # the stored race must be EXACTLY what this call would
                # probe: a narrower earlier race must not answer a
                # wider one (k values never timed), and the caller
                # must not see candidates or unroll forms it excluded
                want = set()
                for k in candidates:
                    if k < 1 or b % k:
                        continue
                    want.add((k, False))
                    if k > 1 and try_unroll:
                        want.add((k, True))
                if best in results \
                        and results[best] == min(results.values()) \
                        and set(results) == want:
                    return best, results
    results = {}
    for k in candidates:
        if k < 1 or b % k:
            continue
        forms = ((False,) if k == 1 else
                 ((False, True) if try_unroll else (False,)))
        for unroll in forms:
            pred = make_predict_fn(apply_fn, microbatch=k,
                                   unroll=unroll)
            results[(k, unroll)] = _chain_time(
                lambda xv, p: pred(p, xv), [sample_x, params],
                iters=iters)
    best = min(results, key=results.get)
    if lvl >= 1:
        at.record(op_key, sample_x.shape, sample_x.dtype,
                  [int(best[0]), bool(best[1])],
                  timings={_enc_form(k, u): float(t)
                           for (k, u), t in results.items()})
    return best, results
