"""Mixture-of-experts with expert parallelism over the device mesh.

The reference has no MoE (its sparse scaling story is row_sparse
embeddings over the parameter server); on TPU the equivalent
capability-scaling axis is expert parallelism: E experts' weights live
stacked on a leading axis sharded over an 'expert' mesh axis, tokens
are routed with capacity-bounded dense dispatch/combine einsums (the
GShard/Switch formulation — fixed shapes, so XLA can tile it onto the
MXU and insert the all-to-all-style collectives itself), and dropped
tokens fall through a residual path.

Public API:
  top_k_gating(logits, k, capacity)       — dispatch/combine tensors
  moe_apply(expert_fn, stacked_params, gate_w, x, ...)
      — full MoE layer; with ``mesh`` the expert axis is sharded and
        the dispatch/combine contractions ride the mesh collectives.

Note: ``expert_fn`` (and pipeline ``stage_fn``) are compile-cache keys —
pass a *stable* callable (module-level function or a lambda created
once), not a fresh lambda per call, or every invocation recompiles.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["top_k_gating", "moe_apply", "expert_capacity"]


def expert_capacity(n_tokens, n_experts, k=1, capacity_factor=1.25):
    """Per-expert token capacity (GShard: k * T/E * factor, >=1)."""
    return max(1, int(n_tokens * k * capacity_factor / n_experts))


def top_k_gating(logits, k, capacity):
    """Capacity-bounded top-k gating.

    logits: (T, E) router scores.  Returns
      dispatch: (T, E, C) 0/1 — token t goes to expert e at slot c
      combine:  (T, E, C) float — gate-probability weights for the
                return path (rows of dropped tokens are all-zero).
    Fixed shapes throughout: position-in-expert comes from a cumsum
    over the one-hot assignment, tokens past ``capacity`` are dropped
    (standard Switch/GShard semantics).
    """
    t_, e_ = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    dispatch = jnp.zeros((t_, e_, capacity), jnp.float32)
    combine = jnp.zeros((t_, e_, capacity), jnp.float32)
    # iterate the (small, static) k choices; mask out used experts
    masked = probs
    # running per-expert fill count carried across the k rounds
    fill = jnp.zeros((e_,), jnp.int32)
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)                    # (T,)
        gate = jnp.take_along_axis(probs, idx[:, None], 1)[:, 0]
        onehot = jax.nn.one_hot(idx, e_, dtype=jnp.int32)    # (T,E)
        # slot of each token within its expert, offset by prior rounds
        pos = jnp.cumsum(onehot, axis=0) - 1 + fill[None, :]  # (T,E)
        pos_t = (pos * onehot).sum(-1)                        # (T,)
        keep = pos_t < capacity
        slot = jax.nn.one_hot(jnp.clip(pos_t, 0, capacity - 1),
                              capacity, dtype=jnp.float32)    # (T,C)
        d = (onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
             ) * keep[:, None, None]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        fill = fill + (onehot * keep[:, None].astype(jnp.int32)).sum(0)
        masked = jnp.where(onehot.astype(bool), -jnp.inf, masked)
    return dispatch, combine


@functools.partial(jax.jit, static_argnums=(0, 4, 5))
def _moe_core(expert_fn, stacked_params, gate_w, x, k, capacity):
    t_, d_ = x.shape
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    dispatch, combine = top_k_gating(logits, k, capacity)
    # route: (T,E,C),(T,D) -> (E,C,D)
    expert_in = jnp.einsum("tec,td->ecd", dispatch,
                           x.astype(jnp.float32)).astype(x.dtype)
    expert_out = jax.vmap(expert_fn)(stacked_params, expert_in)
    out = jnp.einsum("tec,ecd->td", combine,
                     expert_out.astype(jnp.float32))
    # capacity-dropped tokens pass through unchanged (identity
    # residual, the Switch/GShard overflow semantics)
    routed = jnp.clip(dispatch.sum(axis=(1, 2)), 0.0, 1.0)  # (T,)
    out = out + (1.0 - routed)[:, None] * x.astype(jnp.float32)
    return out.astype(x.dtype)


def moe_apply(expert_fn, stacked_params, gate_w, x, k=1,
              capacity_factor=1.25, mesh=None, axis_name="expert"):
    """Apply a mixture-of-experts layer.

    expert_fn(params_e, tokens) -> tokens : one expert on its (C, D)
    slice.  stacked_params: pytree with leading axis E.  gate_w:
    (D, E) router weights.  x: (T, D) tokens.

    With ``mesh``, expert weights are placed sharded over
    ``mesh[axis_name]`` and the dispatched (E, C, D) tensor inherits
    the expert sharding — XLA turns the routing einsums into the
    cross-device token exchange (all-to-all over ICI).
    """
    e_ = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    capacity = expert_capacity(x.shape[0], e_, k, capacity_factor)
    if mesh is not None:
        stacked_params = jax.device_put(
            stacked_params,
            jax.tree_util.tree_map(
                lambda leaf: NamedSharding(
                    mesh, P(axis_name, *([None] * (leaf.ndim - 1)))),
                stacked_params))
        x = jax.device_put(x, NamedSharding(mesh, P()))
    return _moe_core(expert_fn, stacked_params, gate_w, x, int(k),
                     int(capacity))
