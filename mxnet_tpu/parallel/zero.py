"""ZeRO-1 sharded-server gradient exchange (the TPU-native parameter
server).

Reference parity: ps-lite slices every big array across servers
(``MXNET_KVSTORE_BIGARRAY_BOUND``, kvstore_dist.h EncodeDefaultKey),
each server owns a key shard and runs the SERVER-SIDE optimizer on it
(kvstore_dist_server.h:346), and workers pull back only the updated
slices — the partitioning Rajbhandari et al. rediscovered as ZeRO-1
(SC'20) with the bucketed-collective overlap of PyTorch DDP (Li et
al., VLDB'20; both in PAPERS.md).

TPU-native redesign: instead of one XLA all-reduce per parameter
tensor (54 launches for the r05 dp(16) ResNet-18 dryrun — pure launch
overhead on small tensors) the gradients flatten into a few
dtype-homogeneous FLAT BUCKETS, each bucket ``reduce_scatter``s over
the data axis, the registry optimizer's fused rule runs ONLY on the
locally-owned shard (optimizer state lives sharded — memory and FLOPs
scale with params/N), and the updated param buckets ``all_gather``
back: ~2·buckets collectives of the same total bytes.

This module owns the pieces shared by ``make_train_step``'s
``optimizer_sharding="ps"`` path and the Module-side
:class:`ShardedBucketUpdater` (the ``kvstore='dist_sync'`` mapping):

* :func:`plan_buckets` — greedy dtype-homogeneous packing honoring the
  authentic ``MXNET_KVSTORE_BIGARRAY_BOUND`` split threshold, padded
  so every bucket divides the shard count;
* :func:`flatten_bucket` / :func:`unflatten_bucket` /
  :func:`shard_slice` — the flat layout;
* :func:`collective_bytes` — the HLO collective counter (moved here
  from ``__graft_entry__`` so bench.py and tests share it);
* :class:`ShardedBucketUpdater` — Module's drop-in Updater with
  bucket-sharded optimizer state (gathers to the LEGACY per-param
  ``.states`` layout on save, re-shards on load, so checkpoint files
  stay interchangeable with replicated runs).
"""
from __future__ import annotations

import dataclasses
import re

import numpy as onp

from ..base import MXNetError

__all__ = ["Bucket", "plan_buckets", "flatten_bucket", "unflatten_bucket",
           "bucket_segments", "shard_slice", "collective_bytes",
           "resolve_sharding_env", "resolve_zero_stage",
           "plan_fingerprint", "flat_variant_key",
           "resolve_bucket_variant", "analytic_exchange_bytes",
           "stage3_param_keys", "shard_stage3_params",
           "gather_stage3_params", "overlap_report",
           "export_overlap_trace", "ShardedBucketUpdater"]


# ------------------------------------------------------------ bucket plan
@dataclasses.dataclass(frozen=True)
class Bucket:
    """One dtype-homogeneous flat bucket of whole parameters."""

    dtype: str
    names: tuple          # parameter names, in packing order
    shapes: tuple         # per-name shapes
    offsets: tuple        # per-name start offset in the flat layout
    size: int             # total elements (unpadded)
    padded: int           # size rounded up to a multiple of n_shards
    #: opaque partition key (e.g. effective (lr, wd) of the bucket's
    #: params); params with different groups never share a bucket
    group: object = None

    @property
    def pad(self):
        return self.padded - self.size


def _capacity(capacity=None):
    if capacity is not None:
        return max(1, int(capacity))
    from ..config import get_env

    return max(1, int(get_env("MXNET_KVSTORE_BIGARRAY_BOUND")))


def plan_buckets(params, n_shards, capacity=None, group_key=None):
    """Pack ``{name: array}`` into dtype-homogeneous flat buckets.

    The split threshold is the authentic reference knob: a bucket is
    closed once adding the next parameter would push it past
    ``MXNET_KVSTORE_BIGARRAY_BOUND`` elements (``capacity`` overrides
    the env) — the ps-lite bound above which arrays are sliced across
    servers.  Whole parameters are never split across buckets; a
    single parameter larger than the bound gets a bucket of its own.
    Each bucket is padded to a multiple of ``n_shards`` so
    reduce-scatter/all-gather tile evenly.

    ``group_key`` ({name: hashable}, optional) further partitions
    buckets: params with different keys never share one.  The Module
    updater uses it for effective (lr, wd) hyper-parameter groups so
    per-param ``lr_mult``/``wd_mult`` stay exact under sharding.
    """
    cap = _capacity(capacity)
    n_shards = max(1, int(n_shards))
    per_part = {}
    order = []
    for name, v in params.items():
        dt = str(onp.dtype(getattr(v, "dtype", onp.float32)))
        part = (dt, None if group_key is None else group_key.get(name))
        if part not in per_part:
            per_part[part] = []
            order.append(part)
        per_part[part].append((name, tuple(v.shape)))
    buckets = []
    for part in order:
        dt, grp = part
        cur_names, cur_shapes, cur_offsets, cur_size = [], [], [], 0

        def close():
            nonlocal cur_names, cur_shapes, cur_offsets, cur_size
            if not cur_names:
                return
            padded = -(-cur_size // n_shards) * n_shards
            buckets.append(Bucket(dt, tuple(cur_names), tuple(cur_shapes),
                                  tuple(cur_offsets), cur_size, padded,
                                  grp))
            cur_names, cur_shapes, cur_offsets, cur_size = [], [], [], 0

        for name, shape in per_part[part]:
            n = 1
            for d in shape:
                n *= int(d)
            if cur_names and cur_size + n > cap:
                close()
            cur_names.append(name)
            cur_shapes.append(shape)
            cur_offsets.append(cur_size)
            cur_size += n
        close()
    return buckets


def flatten_bucket(bucket, tree):
    """Concatenate the bucket's parameters (in plan order) from a
    ``{name: array}`` tree into one flat padded array."""
    import jax.numpy as jnp

    parts = [jnp.reshape(tree[n], (-1,)) for n in bucket.names]
    if bucket.pad:
        parts.append(jnp.zeros((bucket.pad,), dtype=parts[0].dtype))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def unflatten_bucket(bucket, flat):
    """Inverse of :func:`flatten_bucket` (padding dropped)."""
    out = {}
    for name, shape, off in zip(bucket.names, bucket.shapes,
                                bucket.offsets):
        n = 1
        for d in shape:
            n *= int(d)
        out[name] = flat[off:off + n].reshape(shape)
    return out


def bucket_segments(bucket):
    """Static per-element segment ids (param index within the bucket;
    padding gets an inert extra segment) for norm-based rules (LARS)
    that need per-parameter reductions over the flat layout.

    Returns (ids int32 ndarray of length ``padded``, num_segments).
    """
    ids = onp.empty((bucket.padded,), onp.int32)
    for i, (shape, off) in enumerate(zip(bucket.shapes, bucket.offsets)):
        n = 1
        for d in shape:
            n *= int(d)
        ids[off:off + n] = i
    ids[bucket.size:] = len(bucket.names)
    return ids, len(bucket.names) + 1


def shard_slice(flat, n_shards, idx):
    """This shard's slice of a flat padded bucket (inside shard_map:
    ``idx`` is the traced ``lax.axis_index``)."""
    return flat.reshape(n_shards, -1)[idx]


def bucket_shard_update(bucket, opt, params, g_sh, state, t, *, n_shards,
                        idx, axis, seg=None, key=None, pallas=None,
                        want_finite=False, w_sh=None):
    """The per-bucket owned-shard update core, shared by
    :meth:`ShardedBucketUpdater._build` and ``make_train_step``'s ps
    step — ONE copy, so the two arms' seg-id slicing and shard layout
    cannot drift apart (their parity IS the checkpoint-interchange
    contract).  Slices this device's shard of the flat param bucket
    and runs the fused rule on it against the already-scattered
    gradient shard ``g_sh``.  Returns ``(w_sh, new_w_sh, new_state)``
    un-gathered, so the caller can finite-gate the update before
    :func:`gather_bucket`.

    ``pallas``: which lowering runs the update — True for the fused
    Pallas bucket kernels (ops/pallas_opt.py: prep + rule + the
    loss-scale finiteness check in ONE VMEM pass), False for the jnp
    ``fused_bucket_update``, None to consult the ``fused_bucket_opt``
    autotune variant at trace time (force > MXNET_PALLAS_OPT > cached
    per-program winner > jnp).  An infeasible kernel (unsupported
    rule/dtype) silently keeps the jnp arm — in a race that just
    means the jnp arm wins.

    ``want_finite=True`` returns a 4th element: the loss-scale verdict
    ``isfinite(g_sh).all()`` of the RAW (pre-dtype-cast) gradient —
    fused into the kernel's pass on the pallas arm, or None on the
    jnp arm (the caller keeps its own jnp check, bit-identical to
    today's)."""
    import jax.numpy as jnp

    if w_sh is None:
        # stages 1/2: params arrive replicated as the named tree and
        # the owned shard is sliced here; stage 3 already HOLDS the
        # shard (params live sharded as flat buckets) and passes it in
        # directly via ``w_sh=`` — same update math either way
        w_sh = shard_slice(flatten_bucket(bucket, params), n_shards, idx)
    seg_sh = None
    if seg is not None:
        ids, nseg = seg
        seg_sh = (shard_slice(jnp.asarray(ids), n_shards, idx), nseg)
    use_pallas = pallas
    if use_pallas is None:
        from ..autotune import variant_choice

        use_pallas = bool(variant_choice("fused_bucket_opt"))
    finite = None
    if use_pallas:
        from ..ops import pallas_opt

        res = pallas_opt.bucket_update(
            opt, w_sh, g_sh, state, t, seg=seg_sh, axis_name=axis,
            with_finite=want_finite)
        if res is not None:
            uw, us, finite = res
            if want_finite:
                return w_sh, uw, us, finite
            return w_sh, uw, us
    # the gradient may arrive in a wider dtype than the bucket (the ps
    # step's f32 unscale): cast here so both arms and both callers
    # share one rule (a no-op when dtypes already match)
    gq = g_sh.astype(w_sh.dtype)
    kwargs = {}
    if seg_sh is not None:
        kwargs = dict(seg_ids=seg_sh[0], num_segments=seg_sh[1],
                      axis_name=axis)
    uw, us = opt.fused_bucket_update(w_sh, gq, state, t, key=key,
                                     **kwargs)
    if want_finite:
        return w_sh, uw, us, None
    return w_sh, uw, us


def gather_bucket(bucket, w_sh, axis):
    """All-gather an updated shard back to the replicated flat bucket
    and split it per param (tiled, matching :func:`shard_slice`'s
    row-major layout)."""
    import jax

    return unflatten_bucket(
        bucket, jax.lax.all_gather(w_sh, axis, tiled=True))


def flat_variant_key(plan, stage=None):
    """The ``fused_bucket_opt`` autotune key for a bucket plan: the
    total padded element count + lead dtype — what the kernels
    actually stream, shared by the ps train step, the Module updater
    and the bench bucket race so a winner measured by one reaches the
    others on the same plan.

    ``stage`` (MXNET_ZERO_STAGE): stages None/2 share the legacy key —
    stage 2 IS the program every winner so far was measured on, so the
    Module updater's winner still reaches the default train step.
    Stages 1 and 3 wrap the kernel in a different exchange (all-reduce
    + slice / persistently-sharded params), so they get their own key
    dimension rather than inheriting a winner measured elsewhere."""
    shape = (sum(b.padded for b in plan),)
    if stage not in (None, 2):
        shape = shape + (int(stage),)
    return (shape, plan[0].dtype if plan else "float32")


def resolve_bucket_variant(optimizer, plan, mesh=None, stage=None):
    """Resolve the ``fused_bucket_opt`` lowering for a bucket plan at
    BUILD time: a force scope / MXNET_PALLAS_OPT override first, then
    kernel feasibility, then the cached winner under the flat-layout
    key (stage-distinguished for ZeRO stages 1/3).  Returns True
    (Pallas), False (jnp), or None — undecided, so the trace-time
    ``variant_choice`` consult still applies (force scopes entered
    around a later trace keep working)."""
    from .. import autotune as _at
    from ..ops import pallas_opt

    choice = _at.variant_choice("fused_bucket_opt")
    if choice is not None:
        return bool(choice)
    if not _at.enabled():
        return False
    shape, dtype = flat_variant_key(plan, stage)
    if pallas_opt.supported(optimizer, dtype) is not None:
        return False
    cached = _at.lookup("fused_bucket_opt", shape, dtype,
                        mesh=_at.mesh_desc(mesh))
    if cached is not None:
        return bool(_at.VARIANT_OPS["fused_bucket_opt"].get(cached,
                                                            False))
    return None


def plan_fingerprint(plan, n_shards, stage=None):
    """Stable fingerprint of a bucket plan AT a shard count — the
    checkpoint manifest's ``topology.plan_fingerprint`` (resilience.
    elastic).  Two runs share a fingerprint iff their flat layouts are
    interchangeable: same buckets in the same order with the same
    member names/shapes/dtypes/padding, sharded the same number of
    ways.  A resume whose fingerprint differs must re-plan + re-shard;
    one whose fingerprint matches is a same-topology no-op.

    ``stage``: ZeRO stages None/1/2 hash identically — their params
    (and so their checkpoint payloads) are the replicated named tree,
    interchangeable across stages, and existing stamped checkpoints
    must keep verifying.  Stage 3 persists PARAMETER shards in the
    flat-bucket layout, a different on-disk world: its fingerprint is
    stage-tagged so a cross-stage resume is flagged for re-shard
    instead of silently misreading flat buckets as named tensors."""
    import hashlib

    h = hashlib.sha256()
    h.update(f"shards={int(n_shards)}".encode())
    if stage == 3:
        h.update(b"stage=3")
    for b in plan:
        h.update(repr((b.dtype, b.names, b.shapes, b.offsets,
                       b.size, b.padded, b.group)).encode())
    return h.hexdigest()[:16]


def resolve_sharding_env():
    """The MXNET_OPTIMIZER_SHARDING tri-state: "ps" forced on, False
    forced OFF (overriding kvstore mapping / explicit opt-in), None
    unset (caller decides).  Unknown values raise — a typo'd force-on
    silently training replicated is the silent-green failure mode the
    dryrun case filter also rejects."""
    from ..config import get_env

    raw = str(get_env("MXNET_OPTIMIZER_SHARDING")).strip().lower()
    if raw in ("ps", "1", "on", "true", "yes"):
        return "ps"
    if raw in ("0", "off", "false", "no"):
        return False
    if raw:
        raise MXNetError(
            f"MXNET_OPTIMIZER_SHARDING={raw!r} is not a recognized "
            "value (use 'ps' to force sharding on, '0' to force it "
            "off, or unset)")
    return None


def resolve_zero_stage():
    """The MXNET_ZERO_STAGE knob: 1/2/3 select the exchange stage
    (all-reduce grads / reduce-scatter grads / parameter shards), None
    means unset (the caller's ``zero_stage`` argument decides, default
    stage 2 under sharding).  Unknown values raise — a typo'd stage
    silently training the wrong exchange is the same silent-green
    failure mode MXNET_OPTIMIZER_SHARDING rejects."""
    from ..config import get_env

    raw = str(get_env("MXNET_ZERO_STAGE")).strip()
    if not raw:
        return None
    if raw in ("1", "2", "3"):
        return int(raw)
    raise MXNetError(
        f"MXNET_ZERO_STAGE={raw!r} is not a recognized stage (use 1, "
        "2 or 3, or unset)")


# ------------------------------------------------- stage-3 param layout
def stage3_param_keys(plan):
    """The pytree keys of the stage-3 parameter layout: one flat
    padded bucket per plan entry, sharded over the data axis."""
    return [f"_bucket{i}" for i in range(len(plan))]


def shard_stage3_params(plan, named, mesh=None, data_axis="data"):
    """Named ``{name: array}`` params -> the stage-3 persistent layout
    ``{"_bucket<i>": flat padded array}``, placed sharded over the
    data axis when a mesh is given (per-chip param bytes ~ total/N)."""
    import jax
    import jax.numpy as jnp

    out = {k: flatten_bucket(b, {n: jnp.asarray(named[n])
                                 for n in b.names})
           for k, b in zip(stage3_param_keys(plan), plan)}
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = jax.device_put(out, NamedSharding(mesh, P(data_axis)))
    return out


def gather_stage3_params(plan, pshards):
    """Inverse of :func:`shard_stage3_params`: reassemble the named
    ``{name: array}`` tree from flat bucket arrays (host-side; for a
    multi-process world pass buckets through
    ``resilience.elastic.host_gather`` first)."""
    named = {}
    for k, b in zip(stage3_param_keys(plan), plan):
        named.update(unflatten_bucket(b, onp.asarray(pshards[k])))
    return named


# ---------------------------------------------- analytic exchange bytes
def analytic_exchange_bytes(plan, n_shards, stage):
    """The analytic per-step minimum wire bytes of a bucket plan's
    exchange, in the same accounting :func:`collective_bytes` reads
    off compiled HLO (per-device OUTPUT bytes of each launch):

    * stage 1 — one all-reduce per bucket (``padded`` elements out)
      plus the gather-back all-gather of the updated params;
    * stage 2 — one reduce-scatter per bucket (``padded/N`` out) plus
      the gather-back all-gather (``padded`` out);
    * stage 3 — the forward's per-bucket param all-gather plus the
      backward's reduce-scatter; nothing gathers back.

    The bench/benchdiff collectives-bytes budget gates the measured
    RS+AG bytes at <= 1.05x this floor — anything above it is
    duplicated traffic (a re-gather, an unfused pad) the schedule
    snuck in."""
    rs = ag = ar = 0
    for b in plan:
        item = onp.dtype(b.dtype).itemsize
        full = b.padded * item
        if stage == 1:
            ar += full
            ag += full
        else:
            rs += full // int(n_shards)
            ag += full
    return {"reduce-scatter": rs, "all-gather": ag, "all-reduce": ar}


# -------------------------------------------- overlap proof (Perfetto)
def overlap_report(hlo_text, plan, n_shards):
    """Structural overlap evidence for the stage-3 prefetch, read off
    the compiled step's HLO schedule: every per-bucket parameter
    all-gather is located (matched by its per-device output element
    count = the bucket's ``padded`` total), and for each launch the
    report records how much non-collective compute the schedule placed
    between it and the next bucket's gather (sync schedules, e.g. the
    CPU dryrun) or between its ``-start``/``-done`` pair (async
    schedules, the TPU latency-hiding scheduler).  Overlap is REAL
    when that count is nonzero: bucket k+1's gather is in flight while
    bucket k's consumers run, instead of all collectives serializing
    at the step head.

    Returns ``{"gathers": [{bucket, pos, done_pos, compute_between,
    async}], "total_instructions": int, "overlapped": bool}``."""
    sizes = {}
    for i, b in enumerate(plan):
        sizes.setdefault(b.padded, []).append(i)
    lines = [ln for ln in hlo_text.splitlines() if " = " in ln]
    shape_pat = re.compile(
        r"(f32|bf16|f16|s32|u32|f64|s64|s8|u8|pred)\[([\d,]*)\]")
    ag_pat = re.compile(r"=\s*[\w\[\],{}: /()]*all-gather"
                        r"(-start)?[.\d]*\(")
    done_pat = re.compile(r"all-gather-done")
    gathers = []
    for pos, ln in enumerate(lines):
        m = ag_pat.search(ln)
        if not m:
            continue
        sm = shape_pat.search(ln)
        if not sm:
            continue
        n = 1
        for d in sm.group(2).split(","):
            if d:
                n *= int(d)
        if m.group(1):  # -start carries (operand, result) pairs
            n //= 2
        bucket = sizes.get(n)
        if not bucket:
            continue
        gathers.append({"bucket": bucket[0], "pos": pos,
                        "async": bool(m.group(1)), "done_pos": None,
                        "compute_between": 0})
    is_collective = [bool(re.search("|".join(_COLLECTIVES), ln))
                     for ln in lines]
    for gi, g in enumerate(gathers):
        if g["async"]:
            for pos in range(g["pos"] + 1, len(lines)):
                if done_pat.search(lines[pos]):
                    g["done_pos"] = pos
                    break
            end = g["done_pos"] if g["done_pos"] is not None \
                else g["pos"] + 1
        else:
            end = gathers[gi + 1]["pos"] if gi + 1 < len(gathers) \
                else len(lines)
        g["compute_between"] = sum(
            1 for pos in range(g["pos"] + 1, end)
            if not is_collective[pos])
    return {"gathers": gathers, "total_instructions": len(lines),
            "overlapped": any(g["compute_between"] > 0
                              for g in gathers[:-1] or gathers)}


def export_overlap_trace(report, path, step_ms=1.0, label="zero3"):
    """Render an :func:`overlap_report` onto the Perfetto timeline
    (profiler.py trace-event JSON): a ``collectives`` lane carries one
    span per bucket all-gather and a ``compute`` lane carries the
    schedule segments that run while each gather is in flight —
    schedule positions scaled into a ``step_ms`` window, so lane
    geometry mirrors the compiled schedule even where wall-clock
    per-instruction timing does not exist (inside one jitted program).
    Returns the trace dict after writing it to ``path``."""
    import json

    total = max(1, report["total_instructions"])
    scale = (step_ms * 1000.0) / total  # us per schedule slot
    events = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": f"{label} step (schedule-scaled)"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
         "args": {"name": "collectives (bucket all-gather)"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 2,
         "args": {"name": "compute (hides the next gather)"}},
    ]
    gathers = report["gathers"]
    for gi, g in enumerate(gathers):
        start = g["pos"] * scale
        end_pos = g["done_pos"] if g["done_pos"] is not None else (
            gathers[gi + 1]["pos"] if gi + 1 < len(gathers)
            else total)
        events.append({
            "name": f"all_gather:bucket{g['bucket']}", "ph": "X",
            "cat": "collective", "pid": 1, "tid": 1, "ts": start,
            "dur": max(scale, (end_pos - g["pos"]) * scale),
            "args": {"bucket": g["bucket"], "async": g["async"],
                     "compute_between": g["compute_between"]}})
        if g["compute_between"]:
            events.append({
                "name": f"compute under bucket{g['bucket']} gather",
                "ph": "X", "cat": "compute", "pid": 1, "tid": 2,
                "ts": start + scale,
                "dur": g["compute_between"] * scale,
                "args": {"instructions": g["compute_between"]}})
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace


def check_bucket_rule(optimizer):
    """A bucket shard slices through many parameters, so the rule must
    either be elementwise or provide its own bucket-aware form."""
    from ..optimizer.optimizer import Optimizer

    if getattr(optimizer, "fused_elementwise", True):
        return
    if type(optimizer).fused_bucket_update is Optimizer.fused_bucket_update:
        raise MXNetError(
            f"optimizer {type(optimizer).__name__} is not elementwise and "
            "provides no fused_bucket_update — it cannot run on flat "
            "bucket shards (optimizer_sharding='ps')")


def sharding_rule_reasons(optimizer):
    """Semantics the flat-bucket sharded updater cannot reproduce, as
    human-readable reasons (empty list = eligible).  Module uses this
    at init_optimizer time to fall back to the eager updater with a
    logged reason; :meth:`ShardedBucketUpdater.set_states` uses it to
    REFUSE a resumed pickle that smuggles in such an optimizer (e.g.
    an eager dump carrying an lr_scheduler) instead of silently
    running different math."""
    reasons = []
    try:
        check_bucket_rule(optimizer)
    except MXNetError as e:
        reasons.append(str(e))
    if getattr(optimizer, "needs_key", False):
        reasons.append("stochastic rule (needs per-step PRNG keys)")
    if getattr(optimizer, "multi_precision", False):
        reasons.append("multi_precision master weights")
    if getattr(optimizer, "lr_scheduler", None) is not None:
        reasons.append("lr_scheduler (evaluated per update only in "
                       "the eager path)")
    if not reasons:
        # legacy .states interchange needs identical fused/eager state
        # layouts (Nadam's fused rule carries an extra schedule
        # scalar) — probed HERE so set_states' resume gate refuses the
        # same optimizers Module's init gate does
        import jax.numpy as jnp

        from .. import ndarray as nd

        probe = jnp.zeros((2,), jnp.float32)
        try:
            if len(optimizer.fused_state(probe)) != \
                    len(optimizer.create_state(0, nd.NDArray(probe))):
                reasons.append("fused/eager state layouts differ")
        except Exception as e:
            reasons.append(f"state probe failed: {e!r}")
    return reasons


# ------------------------------------------------- HLO collective counter
_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

_DT_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
             "f64": 8, "s64": 8, "s8": 1, "u8": 1, "pred": 1}


def collective_bytes(hlo_text):
    """Per-collective output bytes + launch counts in a compiled HLO —
    the per-step cross-chip traffic the sharded program will put on
    ICI/DCN.  (Moved from ``__graft_entry__._collective_bytes`` so
    bench.py's collectives phase and the tier-1 budget tests share
    one parser.)"""
    out = {k: 0 for k in _COLLECTIVES}
    counts = {k: 0 for k in _COLLECTIVES}
    pat = re.compile(
        r"= (\(?[\w\[\],{}: /]*\)?) ("
        + "|".join(_COLLECTIVES) + r")(?:-start)?[.\d]*\(")
    shape_pat = re.compile(
        r"(f32|bf16|f16|s32|u32|f64|s64|s8|u8|pred)\[([\d,]*)\]")
    for line in hlo_text.splitlines():
        # async collectives lower to -start/-done pairs: count starts
        m = pat.search(line)
        if not m:
            continue
        shapes, kind = m.groups()
        total = 0
        for sm in shape_pat.finditer(shapes):
            n = 1
            for d in sm.group(2).split(","):
                if d:
                    n *= int(d)
            total += n * _DT_BYTES[sm.group(1)]
        if "-start" in line[m.start():m.end()]:
            # async -start results carry (operand..., result...) pairs
            # (plus tiny u32 contexts): halve to approximate the real
            # wire bytes instead of double-counting
            total //= 2
        out[kind] += total
        counts[kind] += 1
    return {"bytes": out, "counts": counts,
            "total_bytes": sum(out.values())}


# hyper-params NOT fingerprinted for live-mutation re-trace: lr/wd ride
# the bucket group key, multipliers/param_dict feed _get_lr/_get_wd,
# schedulers force the eager fallback, and the counters advance
# mechanically without changing the update rule
_HYPER_SIG_SKIP = frozenset((
    "lr", "wd", "lr_mult", "wd_mult", "param_dict", "idx2name",
    "lr_scheduler", "num_update", "begin_num_update",
    "_index_update_count", "_all_index_update_counts",
))


# --------------------------------------------- Module-side sharded updater
class ShardedBucketUpdater:
    """Module's ZeRO-1 updater: the optimizer state of every trainable
    parameter lives SHARDED over the data mesh in flat buckets; each
    device runs the fused rule only on its shard (the server-side
    optimizer, kvstore_dist_server.h:346) and the updated param buckets
    all-gather back to the replicated executor weights.

    Gradients arriving here are already fully reduced (the executor's
    backward all-reduces under the data mesh), so the win is optimizer
    MEMORY and update FLOPs at params/N per chip — plus one all-gather
    per bucket instead of nothing, which is the ZeRO-1 trade.

    Checkpoint contract (``get_states``/``set_states``): shards GATHER
    into the legacy per-param ``{name: state-tuple}`` pickle on save
    and RE-SHARD on load, so ``.states`` files are bit-interchangeable
    with the replicated :class:`~mxnet_tpu.optimizer.Updater`.
    """

    def __init__(self, optimizer, mesh, params, data_axis="data",
                 capacity=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        check_bucket_rule(optimizer)
        self.optimizer = optimizer
        self.mesh = mesh
        self.axis = data_axis
        self.n_shards = int(mesh.shape[data_axis])
        self._capacity = capacity
        self._shapes = {n: tuple(v.shape) for n, v in params.items()}
        self._dtypes = {n: onp.dtype(getattr(v, "dtype", onp.float32))
                        for n, v in params.items()}
        # effective (lr, wd) per param — lr_mult/wd_mult applied the
        # way the eager Updater would — partition the buckets, so each
        # bucket carries ONE hyper-parameter setting and per-param
        # multipliers survive sharding exactly
        self._groups = self._current_groups(params)
        self.plan = plan_buckets(params, self.n_shards, capacity=capacity,
                                 group_key=self._groups)
        self._rebuild_bucket_opts()
        self._hyper_sig = self._current_hyper_sig()
        self._repl = NamedSharding(mesh, P())
        self._state_sh = NamedSharding(mesh, P(data_axis))
        # the step clock continues the optimizer's (begin_num_update
        # seeds resumed runs; adam/ftml bias correction uses t = _t+1
        # exactly as eager's _update_count would produce)
        self._t = int(getattr(optimizer, "num_update", 0) or 0)
        self._fn = None
        #: which lowering runs the per-shard update: True = the fused
        #: Pallas bucket kernels (ops/pallas_opt), False = jnp; None =
        #: not decided yet (resolved at first _build via the
        #: "fused_bucket_opt" autotune registry — see _decide_variant)
        self._pallas = None
        states = []
        for b in self.plan:
            st = optimizer.fused_state(flatten_bucket(
                b, {n: params[n] for n in b.names}))
            states.append(self._place_state(st))
        self._states = states

    def _current_groups(self, names):
        return {n: (float(self.optimizer._get_lr(n)),
                    float(self.optimizer._get_wd(n))) for n in names}

    def _current_hyper_sig(self):
        """Every scalar hyper-param the fused rules bake in at trace
        time besides lr/wd (momentum, beta1/beta2, rescale_grad,
        clip_gradient, ...).  The eager updater reads these live on
        every update, so a mid-run mutation must re-bake + re-trace
        here too, not silently keep the stale traced values."""
        return tuple(sorted(
            (k, v) for k, v in vars(self.optimizer).items()
            if k not in _HYPER_SIG_SKIP
            and isinstance(v, (int, float, bool, str, bytes, type(None)))
        ))

    def _rebuild_bucket_opts(self):
        """One shallow optimizer copy per bucket with that bucket's
        effective lr/wd baked in (the fused rules read self.lr/self.wd
        at trace time; multipliers live in the group key)."""
        import copy

        self._bucket_opts = []
        for b in self.plan:
            o = copy.copy(self.optimizer)
            o.lr_mult, o.wd_mult, o.param_dict = {}, {}, {}
            o.lr_scheduler = None
            if b.group is not None:
                o.lr, o.wd = b.group
            self._bucket_opts.append(o)

    def _sync_hyper_params(self):
        """The eager updater reads lr/wd on EVERY update; the fused
        path bakes them in at trace time.  Re-deriving the effective
        groups per call keeps the two in sync when the caller mutates
        ``optimizer.lr``/``wd`` mid-training (the epoch-decay recipe):
        a value change re-traces the jitted update, and a change that
        re-partitions the params gathers the states, replans the
        buckets and re-shards.  Non-(lr, wd) scalars (momentum,
        beta1/beta2, rescale_grad, clip_gradient, ...) never affect
        the partition, so a mutation there only re-bakes + re-traces."""
        sig = self._current_hyper_sig()
        if sig != self._hyper_sig:
            self._hyper_sig = sig
            self._rebuild_bucket_opts()
            self._fn = None
        groups = self._current_groups(self._shapes)
        if groups == self._groups:
            return
        if all(len({groups[n] for n in b.names}) == 1
               for b in self.plan):
            # same partition, new values: swap the baked hyper-params
            self._groups = groups
            self.plan = [dataclasses.replace(b, group=groups[b.names[0]])
                         for b in self.plan]
            self._rebuild_bucket_opts()
            self._fn = None
            return
        per_param = self._gather_per_param()
        self._groups = groups

        class _Spec:
            def __init__(self, shape, dtype):
                self.shape, self.dtype = shape, dtype

        self.plan = plan_buckets(
            {n: _Spec(self._shapes[n], self._dtypes[n])
             for n in self._shapes},
            self.n_shards, capacity=self._capacity, group_key=groups)
        self._rebuild_bucket_opts()
        self._states = self._flatten_to_plan(per_param)
        self._fn = None
        self._pallas = None  # new plan = new variant key: re-decide

    def _place_state(self, st):
        import jax

        return tuple(
            jax.device_put(s, self._state_sh if getattr(s, "ndim", 0)
                           else self._repl) for s in st)

    # ----------------------------------------------------------- update
    def _variant_key(self):
        """The autotune cache key for this updater's program — the
        shared flat-layout key (:func:`flat_variant_key`), plus the
        mesh component."""
        from .. import autotune as _at

        shape, dtype = flat_variant_key(self.plan)
        return shape, dtype, _at.mesh_desc(self.mesh)

    def _decide_variant(self):
        """Resolve the "fused_bucket_opt" lowering for this updater —
        the eager-Module analog of make_train_step's in-step race.
        :func:`resolve_bucket_variant` handles the shared precedence
        (force/env override, feasibility, cached flat-key winner);
        undecided on TPU triggers an in-step race of the updater's
        OWN jitted exchange — jnp vs Pallas over the real bucket plan
        with synthetic gradients — whose winner persists under the
        same flat key the ps train step consults.  Off-TPU with no
        override and no cache: jnp (the interpret-mode kernel can only
        lose; racing it would cost minutes to learn that)."""
        from .. import autotune as _at
        from ..ops import pallas_opt

        decided = resolve_bucket_variant(self.optimizer, self.plan,
                                         self.mesh)
        if decided is not None:
            return decided
        if not pallas_opt._on_tpu():
            return False
        shape, dtype, mesh_d = self._variant_key()

        def measure(value):
            return self._time_update(pallas=bool(value))

        winner, _ = _at.tune("fused_bucket_opt", shape, dtype,
                             _at.VARIANT_OPS["fused_bucket_opt"],
                             measure, mesh=mesh_d)
        return bool(_at.VARIANT_OPS["fused_bucket_opt"].get(
            winner, False))

    def _time_update(self, pallas):
        """Marginal sec/update of THIS updater's exchange under the
        given lowering: the shared :func:`autotune.chain_time`
        two-K-slope over a non-donating jit of the real mapped update
        on synthetic small gradients — the program that actually runs
        per Module.update."""
        import jax
        import jax.numpy as jnp

        from .. import autotune as _at

        mapped = self._make_mapped(pallas)
        p_shardings, _ = self._shardings()
        params = {n: jnp.zeros(self._shapes[n],
                               dtype=self._dtypes[n].name)
                  for b in self.plan for n in b.names}
        grads = {n: jnp.full(self._shapes[n], 1e-3,
                             dtype=self._dtypes[n].name)
                 for n in params}
        params = jax.device_put(params,
                                {n: p_shardings[n] for n in params})

        def body(carry, i):
            p_, s_ = carry
            return mapped(p_, grads, s_, (i + 1).astype(jnp.float32))

        return _at.chain_time(body, (params, self._states))

    def _make_mapped(self, pallas):
        import jax
        from jax.sharding import PartitionSpec as P

        from . import compat_shard_map

        plan = self.plan
        opts = self._bucket_opts
        n_sh = self.n_shards
        axis = self.axis
        needs_seg = not getattr(self.optimizer, "fused_elementwise",
                                True)
        segs = [bucket_segments(b) for b in plan] if needs_seg else None

        def local_update(params_, grads_, states_, t):
            idx = jax.lax.axis_index(axis)
            new_p, new_states = {}, []
            for i, b in enumerate(plan):
                # grads arrive fully reduced from the executor's
                # backward; the owned shard is just a slice
                g_sh = shard_slice(flatten_bucket(b, grads_), n_sh, idx)
                _, uw, us = bucket_shard_update(
                    b, opts[i], params_, g_sh, states_[i], t,
                    n_shards=n_sh, idx=idx, axis=axis,
                    seg=segs[i] if needs_seg else None, pallas=pallas)
                new_p.update(gather_bucket(b, uw, axis))
                new_states.append(us)
            return new_p, new_states

        p_specs = {n: P() for b in plan for n in b.names}
        s_specs = [tuple(P(axis) if getattr(s, "ndim", 0) else P()
                         for s in st) for st in self._states]
        return compat_shard_map(
            local_update, self.mesh,
            in_specs=(p_specs, p_specs, s_specs, P()),
            out_specs=(p_specs, s_specs))

    def _shardings(self):
        p_shardings = {n: self._repl for b in self.plan
                       for n in b.names}
        s_shardings = [tuple(self._state_sh if getattr(s, "ndim", 0)
                             else self._repl for s in st)
                       for st in self._states]
        return p_shardings, s_shardings

    def _build(self):
        import jax

        if self._pallas is None:
            self._pallas = self._decide_variant()
        mapped = self._make_mapped(self._pallas)
        p_shardings, s_shardings = self._shardings()
        # donate only the states (we own them between calls); the
        # params/grads buffers stay live in the executor's NDArrays
        self._fn = jax.jit(
            mapped,
            in_shardings=(p_shardings, p_shardings, s_shardings, None),
            out_shardings=(p_shardings, s_shardings),
            donate_argnums=(2,))

    def update_all(self, triplets):
        """Apply one step to every ``(name, grad, weight)`` NDArray
        triplet at once (Module.update collects them; per-name calls
        would defeat the bucketing)."""
        import jax.numpy as jnp

        if self._states is None:
            self._gather_per_param()  # raises the state-lost error
        self._sync_hyper_params()
        if self._fn is None:
            self._build()
        trip = {n: (g, w) for n, g, w in triplets}
        plan_names = [n for b in self.plan for n in b.names]
        planned = set(plan_names)
        missing = [n for n in plan_names if n not in trip]
        extra = [n for n in trip if n not in planned]
        if missing or extra:
            raise MXNetError(
                "sharded update param set diverged from the bucket plan "
                f"(missing {missing[:4]}, unplanned {extra[:4]})")
        grads = {n: trip[n][0]._data for n in plan_names}
        weights = {n: trip[n][1] for n in plan_names}
        params = {n: weights[n]._data for n in plan_names}
        # mid-step collective loss (resilience.faultsim dist.collective):
        # fires BEFORE the jitted exchange, so an armed raise surfaces
        # as a failed step with the donated state buffers still intact
        # — the drain checkpoint that follows stays writable
        from ..resilience import faultsim

        faultsim.inject("dist.collective")
        try:
            new_p, self._states = self._fn(params, grads,
                                           self._states,
                                           jnp.float32(self._t + 1))
        except Exception:
            # the jitted call donates the state buffers; if it died
            # mid-execution they are gone and any later get_states
            # (e.g. the preemption drain's final checkpoint) would
            # crash on deleted arrays — mark the loss so it raises a
            # clear error instead.  _t is untouched: the step did not
            # happen.
            if any(getattr(s, "is_deleted", lambda: False)()
                   for st in self._states for s in st):
                self._states = None
            raise
        self._t += 1
        # the eager Updater advances optimizer.num_update on every call
        # (_update_count); callbacks reading module._optimizer.num_update
        # — the classic decay-every-K-updates recipe — must see the same
        # clock here (num_update is in _HYPER_SIG_SKIP, so this never
        # triggers a re-trace)
        self.optimizer.num_update = max(
            self._t, int(getattr(self.optimizer, "num_update", 0)))
        for n, w in weights.items():
            w._adopt(new_p[n])

    def topology(self):
        """This updater's contribution to the checkpoint ``topology``
        block: shard count, bucket-plan fingerprint, bucket count."""
        return {"world_size": self.n_shards,
                "plan_fingerprint": plan_fingerprint(self.plan,
                                                     self.n_shards),
                "n_buckets": len(self.plan)}

    # --------------------------------------- checkpoint (legacy layout)
    def _gather_per_param(self):
        """Gather the sharded bucket states to host, re-split per
        param: ``{name: tuple of onp leaves}``."""
        if self._states is None:
            raise MXNetError(
                "sharded optimizer state was lost when a step failed "
                "mid-execution (the buffers are donated to the jitted "
                "update); restore from the last checkpoint via "
                "set_states before saving or updating again")
        per_param = {}
        for b, st in zip(self.plan, self._states):
            per_leaf = [onp.asarray(s) for s in st]
            for name, shape, off in zip(b.names, b.shapes, b.offsets):
                n = 1
                for d in shape:
                    n *= int(d)
                per_param[name] = tuple(
                    s[off:off + n].reshape(shape)
                    if getattr(s, "ndim", 0) else s for s in per_leaf)
        return per_param

    def _flatten_to_plan(self, per_param):
        """Inverse of :meth:`_gather_per_param`: flatten per-param
        leaf tuples into the current plan's buckets and re-shard."""
        import jax.numpy as jnp

        new_states = []
        for b in self.plan:
            ref = per_param[b.names[0]]
            flat = []
            for li in range(len(ref)):
                if getattr(ref[li], "ndim", 0):
                    tree = {n: jnp.asarray(per_param[n][li])
                            for n in b.names}
                    flat.append(flatten_bucket(b, tree))
                else:
                    # replicated scalar state: identical across params
                    # by construction
                    flat.append(jnp.asarray(ref[li]))
            new_states.append(self._place_state(tuple(flat)))
        return new_states

    def get_states(self, dump_optimizer=False):
        """Gather the bucket shards back into the legacy per-param
        ``{name: state-tuple-of-NDArrays}`` pickle (the replicated
        Updater's exact on-disk layout, so sharded and replicated runs
        exchange ``.states`` files freely)."""
        import copy
        import pickle

        from .. import ndarray as nd

        states = {
            name: tuple(nd.array(leaf) for leaf in leaves)
            for name, leaves in self._gather_per_param().items()
        }
        # the fused rules take the step count t explicitly (bias
        # correction: adam/ftml/...), so it must ride the pickle — as a
        # reserved entry the eager Updater carries through untouched
        # (it only ever looks states up by param name)
        states["__step"] = (nd.array(onp.asarray([self._t],
                                                 onp.int64)),)
        if dump_optimizer:
            opt = copy.copy(self.optimizer)
            opt.param_dict = {}
            # the sharded path never ran opt._update_count, so the
            # copy's begin_num_update/_index_update_count are stale
            # (num_update is kept live by update_all): seed all three
            # coherently with our step count so an EAGER resume of this
            # file continues its adam/ftml bias correction instead of
            # restarting at t=1
            opt.num_update = opt.begin_num_update = self._t
            opt._index_update_count = {}
            return pickle.dumps((states, opt))
        return pickle.dumps(states)

    def set_states(self, states):
        """Re-shard a legacy per-param states pickle onto the mesh
        (the inverse of :meth:`get_states`; a replicated run's file
        loads the same way)."""
        import pickle

        import jax.numpy as jnp

        loaded = pickle.loads(states)
        have_opt = isinstance(loaded, tuple) and len(loaded) == 2
        if have_opt:
            loaded, new_opt = loaded
            # init_optimizer's eligibility gate ran against the
            # init-time optimizer only; a cross-mode resume can smuggle
            # in semantics the flat buckets cannot reproduce (an eager
            # dump's lr_scheduler would silently pin the lr at the
            # resume-point value).  Refuse loudly, keeping our own
            # optimizer untouched.
            bad = sharding_rule_reasons(new_opt)
            if bad:
                raise MXNetError(
                    "resumed optimizer states carry an optimizer the "
                    "sharded updater cannot run ({}); resume this "
                    "checkpoint with kvstore='local' (the eager "
                    "updater) instead".format("; ".join(bad)))
            new_opt.param_dict = getattr(self.optimizer, "param_dict", {})
            self.optimizer = new_opt
            self._rebuild_bucket_opts()
            self._hyper_sig = self._current_hyper_sig()
            self._fn = None  # hyper-params may have changed: re-trace
            self._pallas = None  # a new optimizer may change kernel
            #                      eligibility: re-decide the lowering
            # dumps carry the count on the optimizer itself — and it is
            # FRESHER than any "__step" states entry: an eager run that
            # resumed a sharded file carries the old "__step" inert
            # while its own counters kept advancing
            self._t = int(getattr(new_opt, "num_update", self._t))
        loaded = dict(loaded)
        stp = loaded.pop("__step", None)
        if stp is not None and not have_opt:
            v = stp[0]
            self._t = int(onp.asarray(
                v.asnumpy() if hasattr(v, "asnumpy") else v
            ).reshape(-1)[0])
        per_param = {}
        for b in self.plan:
            for name in b.names:
                st = loaded.get(name)
                if st is None:
                    raise MXNetError(
                        f"optimizer states missing parameter {name!r}")
                per_param[name] = tuple(
                    s._data if hasattr(s, "_data") else jnp.asarray(s)
                    for s in st)
        self._states = self._flatten_to_plan(per_param)
