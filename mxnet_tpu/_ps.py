"""Sharded TCP parameter-server backend for DistKVStore.

Reference parity: the ps-lite server group + KVStoreDistServer
(src/kvstore/kvstore_dist_server.h).  Every worker PROCESS also runs a
server thread owning a hash shard of the keys — the reference's
EncodeDefaultKey key-to-server sharding (src/kvstore/kvstore_dist.h:606)
— so per-worker wire traffic is O(N) per push/pull, never O(W*N).

Two application modes, matching the reference server:
  * sync  — "wait for all W workers, merge, then update"
            (kvstore_dist_server.h:346-359 DataHandleDefault).  Pulls
            block until the in-flight merge round completes.
  * async — each worker's push applies IMMEDIATELY on arrival; no
            worker ever waits for a peer (the dist_async contract).

Dead-node detection: every worker heartbeats server 0; the
``num_dead_node(timeout)`` probe is the reference's
``get_num_dead_node`` floor (include/mxnet/kvstore.h:380).

Transport: length-prefixed pickled tuples over TCP between trusted
cluster peers (the reference trusts its ps-lite peers the same way).
Server addresses are exchanged through the jax.distributed coordinator
KV service; single-host jobs fall back to loopback derived ports.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

import numpy as onp

from .base import MXNetError

_LEN = struct.Struct("!Q")


def _send_msg(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


def _decompress_2bit(payload, shape, threshold):
    """Unpack the 2-bit wire payload (see GradientCompression) on the
    server side, numpy-only: code 1 -> +t, 2 -> -t, 0 -> 0."""
    p = onp.frombuffer(payload, dtype=onp.uint8)
    codes = onp.stack(
        [p & 3, (p >> 2) & 3, (p >> 4) & 3, (p >> 6) & 3], axis=-1
    ).reshape(-1)
    n = 1
    for d in shape:
        n *= d
    codes = codes[:n].reshape(shape)
    out = onp.zeros(shape, onp.float32)
    out[codes == 1] = threshold
    out[codes == 2] = -threshold
    return out


class _ServerShard(threading.Thread):
    """One process's server: owns keys with hash(key) % size == rank."""

    def __init__(self, rank, size):
        super().__init__(daemon=True, name=f"ps-server-{rank}")
        self.rank = rank
        self.size = size
        self.values = {}           # key -> onp.ndarray (fp32 master)
        self.pending = {}          # key -> merge accumulator (sync mode)
        self.pending_count = {}
        # round bookkeeping for sync pulls: a pull by worker s must wait
        # until every round s has PUSHED is merged — waiting on "no
        # in-flight merge" deadlocks when a fast worker opens round N+1
        # before a slow one pulls round N
        self.completed_rounds = {}   # key -> merged round count
        self.pushed_rounds = {}      # (key, sender) -> pushes by sender
        # keys are namespaced per KVStore instance ("s0/weight"); each
        # namespace can carry its own optimizer rule
        self.updaters = {}         # namespace -> updater callable
        self.last_hb = {}          # worker rank -> monotonic time
        self._cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = False

    def run(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                try:
                    resp = self._handle(msg)
                except Exception as exc:  # surface to the CLIENT —
                    # dying silently leaves the peer blocked in recv
                    # with a misleading 'peer closed'
                    resp = ("err", repr(exc))
                _send_msg(conn, resp)
        except (ConnectionError, EOFError, OSError):
            conn.close()

    # ----------------------------------------------------------- logic
    def _updater_for(self, key):
        ns = key.split("/", 1)[0] if "/" in key else ""
        return self.updaters.get(ns)

    def _apply(self, key, grad):
        """Immediate update (async) / post-merge update (sync)."""
        updater = self._updater_for(key)
        if updater is None:
            # no optimizer on the server: sync replaces the value with
            # the merged sum (the bare push/pull-sum contract); async
            # accumulates (each arrival folds in, there is no "round")
            return grad
        from . import ndarray as nd

        bare = key.split("/", 1)[1] if "/" in key else key
        stored = nd.array(self.values[key])
        updater(bare, nd.array(grad), stored)
        return onp.asarray(stored.asnumpy(), onp.float32)

    def _handle(self, msg):
        op = msg[0]
        if op == "init":
            _, key, value, sender = msg
            with self._cv:
                # rank-0's init wins (reference: the server keeps the
                # first controller-blessed value)
                if sender == 0 or key not in self.values:
                    self.values[key] = onp.asarray(value, onp.float32)
                self._cv.notify_all()
            return ("ok",)
        if op == "push":
            _, key, payload, mode, meta = msg
            sender = meta.get("sender", -1)
            if meta.get("compressed"):
                grad = _decompress_2bit(payload, meta["shape"],
                                        meta["threshold"])
            else:
                grad = onp.asarray(payload, onp.float32)
            with self._cv:
                if key not in self.values:
                    raise MXNetError(f"push to uninitialized key {key}")
                if mode == "async":
                    if self._updater_for(key) is None:
                        self.values[key] = self.values[key] + grad
                    else:
                        self.values[key] = self._apply(key, grad)
                else:  # sync: merge all W, then update once
                    self.pushed_rounds[(key, sender)] = \
                        self.pushed_rounds.get((key, sender), 0) + 1
                    acc = self.pending.get(key)
                    self.pending[key] = grad if acc is None else acc + grad
                    cnt = self.pending_count.get(key, 0) + 1
                    if cnt == self.size:
                        merged = self.pending.pop(key)
                        self.pending_count[key] = 0
                        self.completed_rounds[key] = \
                            self.completed_rounds.get(key, 0) + 1
                        if self._updater_for(key) is None:
                            self.values[key] = merged
                        else:
                            self.values[key] = self._apply(key, merged)
                    else:
                        self.pending_count[key] = cnt
                self._cv.notify_all()
            return ("ok",)
        if op == "pull":
            _, key, sender = msg
            deadline = time.monotonic() + 600.0
            with self._cv:
                # wait for init, and for every round THIS worker pushed
                # to be merged (round-aware: other workers may already
                # be pushing the next round)
                def ready():
                    if key not in self.values:
                        return False
                    need = self.pushed_rounds.get((key, sender), 0)
                    return self.completed_rounds.get(key, 0) >= need
                while not ready():
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise MXNetError(f"pull timeout on key {key}")
                    self._cv.wait(timeout=min(left, 1.0))
                return ("val", self.values[key])
        if op == "hb":
            _, sender = msg
            with self._cv:
                self.last_hb[sender] = time.monotonic()
            return ("ok",)
        if op == "dead":
            _, timeout_s = msg
            now = time.monotonic()
            with self._cv:
                dead = [r for r in range(self.size)
                        if now - self.last_hb.get(r, -1e18) > timeout_s]
            return ("dead", dead)
        raise MXNetError(f"unknown ps op {op!r}")

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


class PSBackend:
    """Worker-side client + in-process server shard (one per process)."""

    _singleton = None

    @classmethod
    def get(cls, rank, size):
        if cls._singleton is None:
            cls._singleton = cls(rank, size)
        return cls._singleton

    def __init__(self, rank, size):
        self.rank = rank
        self.size = size
        self.server = _ServerShard(rank, size)
        self.server.start()
        self._addrs = self._exchange_addrs()
        self._conns = {}
        self._conn_locks = {}
        self._conn_create = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb = threading.Thread(target=self._heartbeat_loop,
                                    daemon=True, name="ps-heartbeat")
        self._hb.start()

    # ----------------------------------------------------- bootstrap
    def _exchange_addrs(self):
        host = socket.gethostname()
        try:
            my_ip = socket.gethostbyname(host)
        except OSError:
            my_ip = "127.0.0.1"
        mine = f"{my_ip}:{self.server.port}"
        if self.size == 1:
            return {0: mine}
        from jax._src import distributed as _jd

        client = _jd.global_state.client
        if client is None:
            raise MXNetError(
                "parameter-server backend needs jax.distributed (launch "
                "with tools/launch.py) for address exchange")
        client.key_value_set(f"mxps/addr/{self.rank}", mine)
        addrs = {}
        for r in range(self.size):
            addrs[r] = client.blocking_key_value_get(
                f"mxps/addr/{r}", 60_000)
        return addrs

    def _conn(self, r):
        # guarded: the heartbeat thread and the worker thread race to
        # open the first connection; an unguarded check-then-create left
        # two sockets sharing one dict slot and corrupted the framing
        with self._conn_create:
            if r not in self._conns:
                host, port = self._addrs[r].rsplit(":", 1)
                s = socket.create_connection((host, int(port)),
                                             timeout=600)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns[r] = s
                self._conn_locks[r] = threading.Lock()
        return self._conns[r], self._conn_locks[r]

    def _request(self, r, msg):
        sock, lock = self._conn(r)
        with lock:
            _send_msg(sock, msg)
            resp = _recv_msg(sock)
        if resp[0] == "val":
            return resp[1]
        if resp[0] == "dead":
            return resp[1]
        if resp[0] == "err":
            raise MXNetError(f"ps server error: {resp[1]}")
        return None

    def owner(self, key):
        # stable across processes (NOT python hash(): PYTHONHASHSEED)
        import zlib

        return zlib.crc32(str(key).encode()) % self.size

    # ----------------------------------------------------- operations
    def init(self, key, value):
        self._request(self.owner(key),
                      ("init", key, onp.asarray(value, onp.float32),
                       self.rank))

    def push(self, key, grad, mode, compressed_payload=None, meta=None):
        if compressed_payload is not None:
            payload = compressed_payload
            meta = dict(meta or {})
            meta["compressed"] = True
        else:
            payload = onp.asarray(grad, onp.float32)
            meta = {"compressed": False}
        meta["sender"] = self.rank
        self._request(self.owner(key), ("push", key, payload, mode, meta))

    def pull(self, key):
        return self._request(self.owner(key), ("pull", key, self.rank))

    def set_updater(self, namespace, updater):
        # in-process: this rank's shard applies with this updater; all
        # ranks run the same program so every shard gets the same rule
        self.server.updaters[namespace] = updater

    def num_dead_node(self, timeout_s=60.0):
        """Count workers whose heartbeat is older than ``timeout_s``
        (reference get_num_dead_node, include/mxnet/kvstore.h:380)."""
        dead = self._request(0, ("dead", float(timeout_s)))
        return len(dead)

    def dead_nodes(self, timeout_s=60.0):
        return self._request(0, ("dead", float(timeout_s)))

    def _heartbeat_loop(self):
        # DEDICATED connection: the shared per-server socket is held
        # for the full duration of a blocking sync pull, and a worker
        # silently not heartbeating while it WAITS would make the
        # liveness probe report healthy-but-blocked workers dead —
        # the exact confusion the probe exists to resolve
        interval = float(os.environ.get("MXNET_PS_HEARTBEAT_SEC", "0.3"))
        conn = None
        while not self._hb_stop.is_set():
            try:
                if conn is None:
                    host, port = self._addrs[0].rsplit(":", 1)
                    conn = socket.create_connection(
                        (host, int(port)), timeout=30)
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                _send_msg(conn, ("hb", self.rank))
                _recv_msg(conn)
            except Exception:
                try:
                    if conn is not None:
                        conn.close()
                except OSError:
                    pass
                conn = None
            self._hb_stop.wait(interval)

    def stop_heartbeat(self):
        """Test hook: a worker that stops heartbeating is 'dead' to the
        liveness probe after the timeout."""
        self._hb_stop.set()
