"""Sharded TCP parameter-server backend for DistKVStore.

Reference parity: the ps-lite server group + KVStoreDistServer
(src/kvstore/kvstore_dist_server.h).  Every worker PROCESS also runs a
server thread owning a hash shard of the keys — the reference's
EncodeDefaultKey key-to-server sharding (src/kvstore/kvstore_dist.h:606)
— so per-worker wire traffic is O(N) per push/pull, never O(W*N).

Two application modes, matching the reference server:
  * sync  — "wait for all W workers, merge, then update"
            (kvstore_dist_server.h:346-359 DataHandleDefault).  Pulls
            block until the in-flight merge round completes.
  * async — each worker's push applies IMMEDIATELY on arrival; no
            worker ever waits for a peer (the dist_async contract).

Dead-node detection: every worker heartbeats server 0; the
``num_dead_node(timeout)`` probe is the reference's
``get_num_dead_node`` floor (include/mxnet/kvstore.h:380).

Transport: the server shard is NATIVE C++ (src/ps_server_native.cc,
built on first use like the recordio decoder — the runtime analog of
ps-lite's C++ server) speaking a little-endian binary protocol; when
the toolchain is unavailable (or MXNET_PS_NATIVE=0) a pure-Python
shard speaking length-prefixed pickle serves instead.  Each shard
advertises its protocol in the exchanged address ("n:host:port" /
"p:host:port"), so clients pick the right codec per server and mixed
clusters still work.  Both transports trust their cluster peers, as
the reference trusts its ps-lite peers.  Addresses are exchanged
through the jax.distributed coordinator KV service.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import socket
import struct
import threading
import time

import numpy as onp

from .base import MXNetError
from .resilience import faultsim
from .resilience.retry import retry_call

_LEN = struct.Struct("!Q")


def _deadline_sec():
    """Skew/readiness wait budget (was four hard-coded 600 s
    constants): MXNET_PS_DEADLINE_SEC, read per-wait so tests can
    lower it at runtime."""
    from .config import get_env

    return float(get_env("MXNET_PS_DEADLINE_SEC"))


def _send_msg(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


def _decompress_2bit(payload, shape, threshold):
    """Unpack the 2-bit wire payload (see GradientCompression) on the
    server side, numpy-only: code 1 -> +t, 2 -> -t, 0 -> 0."""
    p = onp.frombuffer(payload, dtype=onp.uint8)
    codes = onp.stack(
        [p & 3, (p >> 2) & 3, (p >> 4) & 3, (p >> 6) & 3], axis=-1
    ).reshape(-1)
    n = 1
    for d in shape:
        n *= d
    codes = codes[:n].reshape(shape)
    out = onp.zeros(shape, onp.float32)
    out[codes == 1] = threshold
    out[codes == 2] = -threshold
    return out


class _ServerShard(threading.Thread):
    """One process's server: owns keys with hash(key) % size == rank."""

    def __init__(self, rank, size):
        super().__init__(daemon=True, name=f"ps-server-{rank}")
        self.rank = rank
        self.size = size
        self.values = {}           # key -> onp.ndarray (fp32 master)
        self.pending = {}          # key -> merge accumulator (sync mode)
        self.pending_count = {}
        # round bookkeeping for sync pulls: a pull by worker s must wait
        # until every round s has PUSHED is merged — waiting on "no
        # in-flight merge" deadlocks when a fast worker opens round N+1
        # before a slow one pulls round N
        self.completed_rounds = {}   # key -> merged round count
        self.pushed_rounds = {}      # (key, sender) -> pushes by sender
        # keys are namespaced per KVStore instance ("s0/weight"); each
        # namespace can carry its own optimizer rule
        self.updaters = {}         # namespace -> updater callable
        self.last_hb = {}          # worker rank -> monotonic time
        # server-side profiling (reference KVStoreServerProfilerCommand,
        # include/mxnet/kvstore.h:49): op counters + wire bytes,
        # controlled by worker "cmd" frames
        self.profiling = False
        self.stats = {"push": 0, "pull": 0, "spush": 0, "spull": 0,
                      "bytes_in": 0, "bytes_out": 0}
        self.commands = []         # (head, body) log for kController
        self._live_conns = set()
        self._cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = False

    def run(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._cv:
                self._live_conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                try:
                    resp = self._handle(msg)
                except Exception as exc:  # surface to the CLIENT —
                    # dying silently leaves the peer blocked in recv
                    # with a misleading 'peer closed'
                    resp = ("err", repr(exc))
                _send_msg(conn, resp)
        except (ConnectionError, EOFError, OSError):
            conn.close()
        finally:
            with self._cv:
                self._live_conns.discard(conn)

    # ----------------------------------------------------------- logic
    def _prof(self, op, bytes_in=0, bytes_out=0):
        """Profiling counters; caller holds the lock."""
        if self.profiling:
            self.stats[op] += 1
            self.stats["bytes_in"] += int(bytes_in)
            self.stats["bytes_out"] += int(bytes_out)

    def _updater_for(self, key):
        ns = key.split("/", 1)[0] if "/" in key else ""
        return self.updaters.get(ns)

    def _apply(self, key, grad):
        """Immediate update (async) / post-merge update (sync)."""
        updater = self._updater_for(key)
        if updater is None:
            # no optimizer on the server: sync replaces the value with
            # the merged sum (the bare push/pull-sum contract); async
            # accumulates (each arrival folds in, there is no "round")
            return grad
        from . import ndarray as nd

        bare = key.split("/", 1)[1] if "/" in key else key
        stored = nd.array(self.values[key])
        updater(bare, nd.array(grad), stored)
        return onp.asarray(stored.asnumpy(),
                           self.values[key].dtype)

    def _handle(self, msg):
        op = msg[0]
        if op == "init":
            _, key, value, sender, *rest = msg
            refill = bool(rest[0]) if rest else False
            with self._cv:
                # rank-0's init wins (reference: the server keeps the
                # first controller-blessed value) — EXCEPT refills
                # (shard-restart recovery), which are set-if-absent so
                # a late refill never clobbers re-accumulated pushes
                if (sender == 0 and not refill) \
                        or key not in self.values:
                    # store the PUSHED dtype (reference
                    # kvstore_dist_server.h stores recvd blobs as-is;
                    # the old unconditional f32 cast silently degraded
                    # f64 keys and corrupted int keys)
                    self.values[key] = onp.asarray(value)
                self._cv.notify_all()
            return ("ok",)
        if op == "push":
            _, key, payload, mode, meta = msg
            sender = meta.get("sender", -1)
            if meta.get("compressed"):
                grad = _decompress_2bit(payload, meta["shape"],
                                        meta["threshold"])
            else:
                grad = onp.asarray(payload)
            with self._cv:
                if key not in self.values:
                    raise MXNetError(f"push to uninitialized key {key}")
                stored_dt = self.values[key].dtype
                half = stored_dt == onp.float16 \
                    or stored_dt.name == "bfloat16"
                if mode != "async" and half:
                    # sync merges half-precision keys in fp32 (the
                    # native shard widens through double): per-addition
                    # f16/bf16 rounding across W workers diverged from
                    # the native transport — the stored dtype applies
                    # only at the end-of-round apply below
                    grad = grad.astype(onp.float32)
                elif grad.dtype != stored_dt:
                    # the stored dtype never changes after init
                    grad = grad.astype(stored_dt)
                self._prof("push", bytes_in=getattr(grad, "nbytes", 0))
                if mode == "async":
                    if self._updater_for(key) is None:
                        self.values[key] = self.values[key] + grad
                    else:
                        self.values[key] = self._apply(key, grad)
                else:  # sync: merge all W, then update once
                    # round-skew guard: a second push from the same
                    # worker before the in-flight round merges would
                    # collapse two of its grads into one round — WAIT
                    # for the merge instead (each client connection has
                    # its own serve thread, so blocking here only
                    # stalls the skewed sender; its peers' pushes
                    # arrive on their own connections and complete the
                    # round)
                    prev = self.pushed_rounds.get((key, sender), 0)
                    skew_deadline = time.monotonic() + _deadline_sec()
                    while prev > self.completed_rounds.get(key, 0):
                        left = skew_deadline - time.monotonic()
                        if left <= 0:
                            raise MXNetError(
                                f"sync push round skew on {key}: "
                                f"worker {sender} is a full round "
                                "ahead and the merge never completed")
                        self._cv.wait(timeout=min(left, 1.0))
                    self.pushed_rounds[(key, sender)] = prev + 1
                    acc = self.pending.get(key)
                    self.pending[key] = grad if acc is None else acc + grad
                    cnt = self.pending_count.get(key, 0) + 1
                    if cnt == self.size:
                        merged = self.pending.pop(key)
                        if merged.dtype != stored_dt:
                            # apply-time cast: ONE rounding of the
                            # fp32-accumulated round sum
                            merged = merged.astype(stored_dt)
                        self.pending_count[key] = 0
                        self.completed_rounds[key] = \
                            self.completed_rounds.get(key, 0) + 1
                        if self._updater_for(key) is None:
                            self.values[key] = merged
                        else:
                            self.values[key] = self._apply(key, merged)
                    else:
                        self.pending_count[key] = cnt
                self._cv.notify_all()
            return ("ok",)
        if op == "spush":
            # row_sparse push: only (rows, vals) crossed the wire
            # (reference kvstore_dist.h PushRowSparse); the server's
            # store stays dense — the WIRE is what is O(nnz)
            _, key, rows, vals, mode, meta = msg
            sender = meta.get("sender", -1)
            rows = onp.asarray(rows, onp.int64)
            with self._cv:
                if key not in self.values:
                    raise MXNetError(f"spush to uninitialized key {key}")
                stored_dt = self.values[key].dtype
                half = stored_dt == onp.float16 \
                    or stored_dt.name == "bfloat16"
                # sync rounds merge half-precision keys in fp32 (see
                # the dense push path / native-shard double widening)
                merge_dt = onp.float32 if (mode != "async" and half) \
                    else stored_dt
                vals = onp.asarray(vals, merge_dt)
                self._prof("spush",
                           bytes_in=rows.nbytes + vals.nbytes)
                if mode == "async":
                    onp.add.at(self.values[key], rows, vals)
                else:
                    prev = self.pushed_rounds.get((key, sender), 0)
                    skew_deadline = time.monotonic() + _deadline_sec()
                    while prev > self.completed_rounds.get(key, 0):
                        left = skew_deadline - time.monotonic()
                        if left <= 0:
                            raise MXNetError(
                                f"sync spush round skew on {key}")
                        self._cv.wait(timeout=min(left, 1.0))
                    self.pushed_rounds[(key, sender)] = prev + 1
                    acc = self.pending.get(key)
                    if acc is None:
                        acc = onp.zeros(self.values[key].shape,
                                        merge_dt)
                        self.pending[key] = acc
                    onp.add.at(acc, rows, vals)
                    cnt = self.pending_count.get(key, 0) + 1
                    if cnt == self.size:
                        merged = self.pending.pop(key)
                        if merged.dtype != stored_dt:
                            # apply-time cast of the fp32 round sum
                            merged = merged.astype(stored_dt)
                        self.pending_count[key] = 0
                        self.completed_rounds[key] = \
                            self.completed_rounds.get(key, 0) + 1
                        if self._updater_for(key) is None:
                            self.values[key] = merged
                        else:
                            self.values[key] = self._apply(key, merged)
                    else:
                        self.pending_count[key] = cnt
                self._cv.notify_all()
            return ("ok",)
        if op == "spull":
            # pull ONLY the requested rows (kvstore_dist.h:344
            # PullRowSparseImpl): the response is O(len(rows));
            # rowlen is only needed by the flat-storage native shard
            _, key, rows, sender, _rowlen = msg
            rows = onp.asarray(rows, onp.int64)
            deadline = time.monotonic() + _deadline_sec()
            with self._cv:
                def ready():
                    if key not in self.values:
                        return False
                    need = self.pushed_rounds.get((key, sender), 0)
                    return self.completed_rounds.get(key, 0) >= need
                while not ready():
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise MXNetError(f"spull timeout on key {key}")
                    self._cv.wait(timeout=min(left, 1.0))
                out = self.values[key][rows]
                self._prof("spull", bytes_in=rows.nbytes,
                           bytes_out=out.nbytes)
                return ("val", out)
        if op == "pull":
            _, key, sender = msg
            deadline = time.monotonic() + _deadline_sec()
            with self._cv:
                # wait for init, and for every round THIS worker pushed
                # to be merged (round-aware: other workers may already
                # be pushing the next round)
                def ready():
                    if key not in self.values:
                        return False
                    need = self.pushed_rounds.get((key, sender), 0)
                    return self.completed_rounds.get(key, 0) >= need
                while not ready():
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise MXNetError(f"pull timeout on key {key}")
                    self._cv.wait(timeout=min(left, 1.0))
                self._prof("pull",
                           bytes_out=self.values[key].nbytes)
                return ("val", self.values[key])
        if op == "hb":
            _, sender = msg
            with self._cv:
                self.last_hb[sender] = time.monotonic()
            return ("ok",)
        if op == "cmd":
            # worker->server command channel (reference
            # KVStore::SendCommandToServers, kvstore_dist_server.h
            # CommandHandle).  head==0 carries the profiler protocol;
            # other heads are logged for application use.
            _, head, body = msg
            with self._cv:
                self.commands.append((int(head), str(body)))
                if int(head) == 0:
                    parts = str(body).split(":", 2)
                    if parts[0] == "profile":
                        if parts[1] == "start":
                            self.profiling = True
                            for k in self.stats:
                                self.stats[k] = 0
                        elif parts[1] == "stop":
                            self.profiling = False
                        elif parts[1] == "dump" and len(parts) == 3:
                            import json

                            # per-shard file: every shard receives the
                            # broadcast, so the path gets .r<rank>
                            with open(f"{parts[2]}.r{self.rank}",
                                      "w") as f:
                                json.dump({"rank": self.rank,
                                           "profiling": self.profiling,
                                           **self.stats}, f)
            return ("ok",)
        if op == "dead":
            _, timeout_s = msg
            now = time.monotonic()
            with self._cv:
                dead = [r for r in range(self.size)
                        if now - self.last_hb.get(r, -1e18) > timeout_s]
            return ("dead", dead)
        raise MXNetError(f"unknown ps op {op!r}")

    def stop(self):
        self._stop = True
        # shutdown BEFORE close: a thread blocked in accept() holds a
        # kernel reference that keeps the listener alive (and still
        # accepting!) after close(); shutdown wakes it with an error so
        # the port actually dies
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # a stopped shard must go SILENT: established connections keep
        # serving otherwise, and peers would never fail over to the
        # restarted incarnation
        with self._cv:
            conns = list(self._live_conns)
            self._live_conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


# ------------------------------------------------- native shard loader
_native_lock = threading.Lock()
_native_lib = None
_native_tried = False

_PS_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src", "ps_server_native.cc")

#: ctypes signature of the optimizer callback the native server calls
_UPDATER_CB = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
    ctypes.POINTER(ctypes.c_float), ctypes.c_uint64)


def _get_native_lib():
    """Build + load the C++ shard (same pattern as _native.py's
    recordio decoder); None when the toolchain is absent or
    MXNET_PS_NATIVE=0."""
    global _native_lib, _native_tried
    if os.environ.get("MXNET_PS_NATIVE", "1") == "0":
        return None
    with _native_lock:
        if _native_tried:
            return _native_lib
        _native_tried = True
        try:
            from ._native import build_native

            out = build_native(_PS_SRC, "libps_server_native.so",
                               ldflags=("-lpthread",), opt="-O2")
            lib = ctypes.CDLL(out)
            lib.ps_native_start.restype = ctypes.c_int
            lib.ps_native_start.argtypes = [ctypes.c_int, ctypes.c_int]
            lib.ps_native_set_updater.restype = None
            lib.ps_native_set_updater.argtypes = [_UPDATER_CB]
            _native_lib = lib
        except Exception:
            _native_lib = None
        return _native_lib


# --------------------------------------------- native binary encoding
def _np_bf16():
    import ml_dtypes

    return onp.dtype(ml_dtypes.bfloat16)


def _dt_code(dtype):
    """Wire dtype codes (must match ps_server_native.cc)."""
    name = onp.dtype(dtype).name
    codes = {"float32": 0, "float64": 1, "bfloat16": 2, "float16": 3,
             "int32": 4, "int64": 5, "int8": 6, "uint8": 7}
    if name not in codes:
        return None
    return codes[name]


def _dt_of_code(code):
    if code == 2:
        return _np_bf16()
    return onp.dtype(["float32", "float64", None, "float16", "int32",
                      "int64", "int8", "uint8"][code])


def _wire_array(a):
    """Contiguous array in a wire-supported dtype (unsupported dtypes
    widen to f32, the old behavior)."""
    a = onp.ascontiguousarray(a)
    if _dt_code(a.dtype) is None:
        a = a.astype(onp.float32)
    return a


def _n_encode(msg):
    op_map = {"init": 0, "push": 1, "pull": 2, "hb": 3, "dead": 4,
              "spush": 5, "spull": 6, "cmd": 7}
    op = msg[0]
    key = msg[1] if op in ("init", "push", "pull", "spush",
                           "spull") else ""
    kb = key.encode()
    head = struct.pack("<BI", op_map[op], len(kb)) + kb
    if op == "spush":
        _, _, rows, vals, mode, meta = msg
        rows = onp.ascontiguousarray(rows, onp.int64)
        vals = _wire_array(vals)
        rowlen = vals.size // max(rows.size, 1)
        body = struct.pack(
            "<iBBQQ", meta["sender"], 0 if mode == "sync" else 1,
            _dt_code(vals.dtype), rows.size,
            rowlen) + rows.tobytes() + vals.tobytes()
    elif op == "spull":
        _, _, rows, sender, rowlen = msg
        rows = onp.ascontiguousarray(rows, onp.int64)
        body = struct.pack("<iQQ", sender, rows.size,
                           rowlen) + rows.tobytes()
    elif op == "init":
        _, _, value, sender, *rest = msg
        refill = bool(rest[0]) if rest else False
        v = _wire_array(value)
        body = struct.pack("<iBBQ", sender, 1 if refill else 0,
                           _dt_code(v.dtype), v.size) + v.tobytes()
    elif op == "push":
        _, _, payload, mode, meta = msg
        if meta.get("compressed"):
            n = 1
            for d in meta["shape"]:
                n *= d
            body = struct.pack(
                "<iBBBfQ", meta["sender"], 0 if mode == "sync" else 1,
                1, 0, float(meta["threshold"]), n) + bytes(payload)
        else:
            v = _wire_array(payload)
            body = struct.pack(
                "<iBBBfQ", meta["sender"], 0 if mode == "sync" else 1,
                0, _dt_code(v.dtype), 0.0, v.size) + v.tobytes()
    elif op == "pull":
        body = struct.pack("<i", msg[2])
    elif op == "hb":
        body = struct.pack("<i", msg[1])
    elif op == "cmd":
        _, cmd_head, cbody = msg
        cb = str(cbody).encode()
        body = struct.pack("<iI", int(cmd_head), len(cb)) + cb
    else:  # dead
        body = struct.pack("<d", float(msg[1]))
    frame = head + body
    return struct.pack("<Q", len(frame)) + frame


def _n_roundtrip(sock, msg):
    sock.sendall(_n_encode(msg))
    (ln,) = struct.unpack("<Q", _recv_exact(sock, 8))
    data = _recv_exact(sock, ln)
    status = data[0]
    if status == 0:
        return None
    if status == 1:
        raise MXNetError(f"ps server error: {data[1:].decode()}")
    if status == 2:
        dt = data[1]
        (n,) = struct.unpack_from("<Q", data, 2)
        return onp.frombuffer(data, _dt_of_code(dt), count=n,
                              offset=10).copy()
    if status == 3:
        (m,) = struct.unpack_from("<I", data, 1)
        return list(struct.unpack_from(f"<{m}i", data, 5))
    raise MXNetError(f"ps: bad response status {status}")


class PSBackend:
    """Worker-side client + in-process server shard (one per process).

    The shard is the native C++ server when buildable (protocol tag
    "n:" in the exchanged address), else the Python pickle server
    ("p:"); clients pick the codec per server address, so mixed
    clusters interoperate.
    """

    _singleton = None

    @classmethod
    def get(cls, rank, size):
        if cls._singleton is None:
            cls._singleton = cls(rank, size)
        return cls._singleton

    def __init__(self, rank, size):
        self.rank = rank
        self.size = size
        self._updaters = {}
        self._shapes = {}  # key -> value shape (native shards store flat)
        self._native_cb = None  # keep the ctypes callback alive
        lib = _get_native_lib()
        port = lib.ps_native_start(rank, size) if lib is not None \
            else -1
        if port > 0:
            self._lib = lib
            self.server = None
            self._proto = "n"
            self._port = port
        else:
            self._lib = None
            self.server = _ServerShard(rank, size)
            self.server.start()
            self.server.updaters = self._updaters
            self._proto = "p"
            self._port = self.server.port
        self._addrs = self._exchange_addrs()
        self._conns = {}
        self._conn_locks = {}
        self._conn_create = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb = threading.Thread(target=self._heartbeat_loop,
                                    daemon=True, name="ps-heartbeat")
        self._hb.start()

    # ----------------------------------------------------- bootstrap
    def _kv_client(self):
        from jax._src import distributed as _jd

        client = _jd.global_state.client
        if client is None:
            raise MXNetError(
                "parameter-server backend needs jax.distributed (launch "
                "with tools/launch.py) for address exchange")
        return client

    def _exchange_addrs(self):
        """Epoch-keyed address exchange: a RESTARTED worker (launch.py
        --max-restarts) finds its old incarnation's key still present
        and registers under the next epoch; peers re-resolve on
        connection failure (see _refresh_addr) — the re-registration
        half of the ps-lite node-recovery story."""
        host = socket.gethostname()
        try:
            my_ip = socket.gethostbyname(host)
        except OSError:
            my_ip = "127.0.0.1"
        mine = f"{self._proto}:{my_ip}:{self._port}"
        self._addr_epoch = {r: 0 for r in range(self.size)}
        if self.size == 1:
            return {0: mine}
        client = self._kv_client()
        epoch = 0
        while True:
            try:
                client.key_value_set(
                    f"mxps/addr/{self.rank}/e{epoch}", mine)
                break
            except Exception:  # stale key from a prior incarnation
                epoch += 1
                if epoch > 1000:
                    raise
        addrs = {}
        for r in range(self.size):
            addrs[r] = client.blocking_key_value_get(
                f"mxps/addr/{r}/e0", 60_000)
        self._addr_epoch[self.rank] = epoch
        addrs[self.rank] = mine
        return addrs

    def _refresh_addr(self, r):
        """A peer's shard stopped answering: wait for its restarted
        incarnation to register under the next epoch and adopt the new
        address (blocking up to 120 s — the launcher's relaunch
        window)."""
        if self.size == 1:
            return
        client = self._kv_client()
        e = self._addr_epoch.get(r, 0) + 1
        addr = client.blocking_key_value_get(
            f"mxps/addr/{r}/e{e}", 120_000)
        self._addr_epoch[r] = e
        self._addrs[r] = addr

    def _addr_of(self, r):
        proto, host, port = self._addrs[r].split(":", 2)
        return proto, host, int(port)

    @staticmethod
    def _dial(host, port, timeout):
        """create_connection with TCP self-connect detection: dialing a
        CLOSED localhost port can 'succeed' when the kernel picks the
        same value as the ephemeral source port, yielding a socket
        connected to ITSELF — the client would then read its own
        request back as the response and silently drop the operation
        (observed in the shard-restart drill)."""
        s = socket.create_connection((host, port), timeout=timeout)
        try:
            if s.getsockname() == s.getpeername():
                s.close()
                raise ConnectionError(
                    f"self-connect to {host}:{port} (no listener)")
        except OSError:
            s.close()
            raise
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _conn(self, r):
        # guarded: the heartbeat thread and the worker thread race to
        # open the first connection; an unguarded check-then-create left
        # two sockets sharing one dict slot and corrupted the framing
        with self._conn_create:
            if r not in self._conns:
                _, host, port = self._addr_of(r)
                self._conns[r] = self._dial(host, port, timeout=600)
                self._conn_locks[r] = threading.Lock()
        return self._conns[r], self._conn_locks[r]

    def _do_request(self, r, msg):
        proto = self._addr_of(r)[0]
        sock, lock = self._conn(r)
        with lock:
            if proto == "n":
                return _n_roundtrip(sock, msg)
            _send_msg(sock, msg)
            resp = _recv_msg(sock)
        if resp[0] == "val":
            return resp[1]
        if resp[0] == "dead":
            return resp[1]
        if resp[0] == "err":
            raise MXNetError(f"ps server error: {resp[1]}")
        if resp[0] != "ok":
            # garbage frame (e.g. our own request echoed back): treat
            # as a dead transport so the retry/re-resolve path engages
            raise ConnectionError(f"ps: malformed response {resp[:1]}")
        return None

    #: injection points on the client ops (resilience.faultsim):
    #: armed `raise` faults are retried like real transport errors, so
    #: the backoff path is exercised end-to-end
    _FAULT_POINTS = {"push": "ps.push", "spush": "ps.push",
                     "pull": "ps.pull", "spull": "ps.pull"}

    def _request(self, r, msg):
        point = self._FAULT_POINTS.get(msg[0])

        def once():
            if point is not None:
                faultsim.inject(point)
            return self._do_request(r, msg)

        def on_retry(attempt, exc):
            # TRANSIENT transport failure: drop + redial the same
            # address (a dropped TCP conn on a healthy shard must not
            # stall in the epoch wait below); injected faults keep
            # their connection.
            try:
                # retries are rare: telemetry cost lands only on the
                # failure path, never on a healthy roundtrip
                from . import telemetry

                telemetry.count("ps_retries")
                telemetry.event("ps_retry", op=msg[0], rank=r,
                                attempt=attempt,
                                error=type(exc).__name__)
            except Exception:
                pass
            if not isinstance(exc, faultsim.FaultInjected):
                self._drop_conn(r)

        try:
            # bounded exponential backoff with jitter: at-least-once
            # delivery — an applied-but-unacked push may repeat, the
            # same window ps-lite's resend has
            return retry_call(
                once,
                retry_on=(ConnectionError, EOFError, OSError,
                          faultsim.FaultInjected),
                attempts=3, base_delay=0.05, max_delay=1.0,
                # the TOTAL retry budget is the PS deadline: attempts
                # alone could overshoot it once backoff grows, and a
                # client stuck retrying past the server's own wait
                # deadline is just a slower failure
                deadline_sec=_deadline_sec(),
                on_retry=on_retry)
        except faultsim.FaultInjected:
            raise  # exhausted injected faults stay injected faults
        except (ConnectionError, EOFError, OSError):
            # still dead: wait for a restarted incarnation to register
            # under the next address epoch, then retry once more
            self._drop_conn(r)
            self._refresh_addr(r)
            return self._do_request(r, msg)

    def owner(self, key):
        # stable across processes (NOT python hash(): PYTHONHASHSEED)
        import zlib

        return zlib.crc32(str(key).encode()) % self.size

    # ----------------------------------------------------- operations
    def init(self, key, value, refill=False):
        v = onp.asarray(value)
        self._shapes[key] = v.shape
        self._request(self.owner(key),
                      ("init", key, v, self.rank, refill))

    def push(self, key, grad, mode, compressed_payload=None, meta=None):
        if compressed_payload is not None:
            payload = compressed_payload
            meta = dict(meta or {})
            meta["compressed"] = True
        else:
            payload = onp.asarray(grad)
            meta = {"compressed": False}
        meta["sender"] = self.rank
        self._request(self.owner(key), ("push", key, payload, mode, meta))

    def pull(self, key):
        return self._request(self.owner(key), ("pull", key, self.rank))

    def spush(self, key, rows, vals, mode):
        """Row-sparse push: O(nnz) bytes on the wire, in the value's
        native dtype."""
        rows = onp.ascontiguousarray(rows, onp.int64)
        vals = onp.ascontiguousarray(vals)
        self._request(self.owner(key),
                      ("spush", key, rows, vals, mode,
                       {"sender": self.rank}))

    def spull(self, key, rows):
        """Pull only ``rows`` of the key: O(len(rows)) response."""
        rows = onp.ascontiguousarray(rows, onp.int64)
        shape = self._shapes.get(key)
        rowlen = 1
        if shape is not None and len(shape) >= 1:
            n = 1
            for d in shape[1:]:
                n *= d
            rowlen = n
        out = self._request(self.owner(key),
                            ("spull", key, rows, self.rank, rowlen))
        return onp.asarray(out).reshape(
            (rows.size,) + (tuple(shape[1:]) if shape else ()))

    def set_updater(self, namespace, updater):
        # in-process: this rank's shard applies with this updater; all
        # ranks run the same program so every shard gets the same rule
        self._updaters[namespace] = updater
        if self._lib is not None and self._native_cb is None:
            self._native_cb = _UPDATER_CB(self._native_updater)
            self._lib.ps_native_set_updater(self._native_cb)

    def _native_updater(self, key_c, grad_p, value_p, n):
        """C callback from the native shard: apply the Python-side
        optimizer rule in place.  Returns 0 if applied, 1 if no rule is
        registered for the key's namespace (server falls back to its
        default merge semantics), -1 if the rule RAISED — the server
        surfaces that to the pushing client instead of silently
        merging."""
        try:
            key = key_c.decode()
            ns, _, bare = key.partition("/")
            updater = self._updaters.get(ns)
            if updater is None:
                return 1
            from . import ndarray as nd

            # the native shard stores values flat; give the optimizer
            # rule the ORIGINAL shape (recorded at init on every
            # worker) so axis-dependent rules behave identically on
            # both transports
            shape = self._shapes.get(key, (n,))
            grad = onp.ctypeslib.as_array(
                grad_p, shape=(n,)).copy().reshape(shape)
            value = onp.ctypeslib.as_array(value_p, shape=(n,))
            stored = nd.array(value.copy().reshape(shape))
            updater(bare or key, nd.array(grad), stored)
            value[:] = onp.asarray(stored.asnumpy(),
                                   onp.float32).ravel()
            return 0
        except Exception:
            import traceback

            traceback.print_exc()
            return -1

    def command(self, head, body):
        """Broadcast a (head, body) command to EVERY server shard
        (reference KVStore::SendCommandToServers / ps-lite control).
        head==0 drives server-side profiling: 'profile:start',
        'profile:stop', 'profile:dump:<path>'."""
        for r in range(self.size):
            self._request(r, ("cmd", int(head), str(body)))

    def num_dead_node(self, timeout_s=60.0):
        """Count workers whose heartbeat is older than ``timeout_s``
        (reference get_num_dead_node, include/mxnet/kvstore.h:380).
        Queries shards in rank order and takes the first answer, so the
        probe survives rank-0 shard death (heartbeats FAN OUT to every
        shard)."""
        return len(self.dead_nodes(timeout_s))

    def dead_nodes(self, timeout_s=60.0):
        last_err = None
        for r in range(self.size):
            try:
                # _do_request, NOT _request: the probe must fail over
                # to the next shard immediately, not block waiting for
                # the dead one's restarted incarnation
                return self._do_request(r, ("dead", float(timeout_s)))
            except Exception as e:  # dead shard: ask the next one
                last_err = e
                self._drop_conn(r)
        raise MXNetError(f"liveness probe failed on every shard: "
                         f"{last_err!r}")

    def _drop_conn(self, r):
        with self._conn_create:
            conn = self._conns.pop(r, None)
            self._conn_locks.pop(r, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _heartbeat_loop(self):
        # DEDICATED connections: the shared per-server socket is held
        # for the full duration of a blocking sync pull, and a worker
        # silently not heartbeating while it WAITS would make the
        # liveness probe report healthy-but-blocked workers dead —
        # the exact confusion the probe exists to resolve.
        # FAN-OUT: every shard gets the beat, so the probe keeps
        # working when rank-0's shard dies.
        interval = float(os.environ.get("MXNET_PS_HEARTBEAT_SEC", "0.3"))
        conns = {}
        while not self._hb_stop.is_set():
            for r in range(self.size):
                try:
                    if r not in conns:
                        proto, host, port = self._addr_of(r)
                        # SHORT dial timeout: one blackholed shard must
                        # not starve the beat to the live ones (serial
                        # fan-out; probes run with windows of seconds)
                        c = self._dial(host, port, timeout=2)
                        conns[r] = (proto, c)
                    proto, c = conns[r]
                    if proto == "n":
                        _n_roundtrip(c, ("hb", self.rank))
                    else:
                        _send_msg(c, ("hb", self.rank))
                        _recv_msg(c)
                except Exception:
                    pc = conns.pop(r, None)
                    if pc is not None:
                        try:
                            pc[1].close()
                        except OSError:
                            pass
            self._hb_stop.wait(interval)

    def stop_heartbeat(self):
        """Test hook: a worker that stops heartbeating is 'dead' to the
        liveness probe after the timeout."""
        self._hb_stop.set()
