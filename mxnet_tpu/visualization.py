"""Network visualization (reference python/mxnet/visualization.py).

``print_summary`` (reference :47) — text table of layers, output
shapes, and parameter counts, driven by the symbol's JSON graph +
infer_shape (the same inputs the reference uses).

``plot_network`` (reference :211) — graphviz Digraph of the symbol
graph; requires the optional ``graphviz`` package (gated, like the
reference's ImportError behavior).
"""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _str2tuple(string):
    """'(1,2,3)' -> ['1','2','3'] (reference visualization.py:32)."""
    import re

    return re.findall(r"\d+", str(string))


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Print a layer-by-layer summary table (reference
    visualization.py:47).

    shape: dict of input name -> shape for output-shape inference.
    """
    from .symbol.symbol import Symbol

    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise MXNetError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    # reference quirk kept for parity: set(heads[0]) = {last_node_id,
    # out_idx, 0}, which includes node 0 — so the 'data' variable counts
    # as a predecessor and the first layer's input channels are counted
    heads = set(conf["heads"][0]) if conf.get("heads") else {0}
    positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, pos):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[: pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)
    total_params = 0

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        pre_filter = 0
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
                    if show_shape:
                        key = input_name
                        if input_node["op"] != "null":
                            key += "_output"
                        if key in shape_dict:
                            shape = shape_dict[key][1:]
                            pre_filter = pre_filter + int(shape[0]) \
                                if shape else pre_filter
        cur_param = 0
        attrs = node.get("attrs") or {}
        if op == "Convolution":
            num_group = int(attrs.get("num_group", "1"))
            cur_param = pre_filter * int(attrs["num_filter"]) // num_group
            for k in _str2tuple(attrs["kernel"]):
                cur_param *= int(k)
            if attrs.get("no_bias", "False") not in ("True", "1", "true"):
                cur_param += int(attrs["num_filter"])
        elif op == "FullyConnected":
            cur_param = pre_filter * int(attrs["num_hidden"])
            if attrs.get("no_bias", "False") not in ("True", "1", "true"):
                cur_param += int(attrs["num_hidden"])
        elif op == "BatchNorm":
            key = node["name"] + "_output"
            if show_shape and key in shape_dict:
                cur_param = int(shape_dict[key][1]) * 4
        elif op == "Embedding":
            cur_param = int(attrs["input_dim"]) * int(attrs["output_dim"])
        first_connection = pre_node[0] if pre_node else ""
        fields = [f"{node['name']}({op})",
                  "x".join(str(x) for x in out_shape),
                  cur_param, first_connection]
        print_row(fields, positions)
        for i in range(1, len(pre_node)):
            fields = ["", "", "", pre_node[i]]
            print_row(fields, positions)
        return cur_param

    for i, node in enumerate(nodes):
        out_shape = []
        op = node["op"]
        if op == "null" and i > 0:
            continue
        if op != "null" or i in heads:
            key = node["name"] + ("_output" if op != "null" else "")
            if show_shape and key in shape_dict:
                out_shape = shape_dict[key][1:]
        total_params += print_layer_summary(node, out_shape)
        if i == len(nodes) - 1:
            print("=" * line_length)
        else:
            print("_" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Graphviz Digraph of the symbol graph (reference
    visualization.py:211).  Requires the optional graphviz package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError(
            "Draw network requires graphviz library") from None
    from .symbol.symbol import Symbol

    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    node_attrs = node_attrs or {}
    draw_shape = False
    shape_dict = {}
    if shape is not None:
        draw_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise MXNetError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    # color palette from the reference
    cm = ("#8dd3c7", "#fb8072", "#ffffb3", "#bebada", "#80b1d3",
          "#fdb462", "#b3de69", "#fccde5")

    def looks_like_weight(name):
        weight_like = ("_weight", "_bias", "_beta", "_gamma",
                       "_moving_var", "_moving_mean", "_running_var",
                       "_running_mean")
        return name.endswith(weight_like)

    hidden_nodes = set()
    for node in nodes:
        op = node["op"]
        name = node["name"]
        attrs = {"shape": "box", "fixedsize": "false"}
        label = name
        if op == "null":
            if looks_like_weight(name):
                if hide_weights:
                    hidden_nodes.add(name)
                continue
            attrs["shape"] = "oval"
            attrs["fillcolor"] = cm[0]
        elif op == "Convolution":
            a = node.get("attrs") or {}
            label = "Convolution\n{kernel}/{stride}, {filter}".format(
                kernel="x".join(_str2tuple(a.get("kernel", ""))),
                stride="x".join(_str2tuple(a.get("stride", "1"))),
                filter=a.get("num_filter", "?"))
            attrs["fillcolor"] = cm[1]
        elif op == "FullyConnected":
            a = node.get("attrs") or {}
            label = f"FullyConnected\n{a.get('num_hidden', '?')}"
            attrs["fillcolor"] = cm[1]
        elif op == "BatchNorm":
            attrs["fillcolor"] = cm[3]
        elif op in ("Activation", "LeakyReLU"):
            a = node.get("attrs") or {}
            label = f"{op}\n{a.get('act_type', '')}"
            attrs["fillcolor"] = cm[2]
        elif op == "Pooling":
            a = node.get("attrs") or {}
            label = "Pooling\n{t}, {k}/{s}".format(
                t=a.get("pool_type", "?"),
                k="x".join(_str2tuple(a.get("kernel", ""))),
                s="x".join(_str2tuple(a.get("stride", "1"))))
            attrs["fillcolor"] = cm[4]
        elif op in ("Concat", "Flatten", "Reshape"):
            attrs["fillcolor"] = cm[5]
        elif op == "Softmax" or op == "SoftmaxOutput":
            attrs["fillcolor"] = cm[6]
        else:
            attrs["fillcolor"] = cm[7]
        dot.node(name=name, label=label, **attrs)

    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        for item in node["inputs"]:
            input_node = nodes[item[0]]
            input_name = input_node["name"]
            if input_name in hidden_nodes:
                continue
            attrs = {"dir": "back", "arrowtail": "open"}
            if draw_shape:
                key = input_name
                if input_node["op"] != "null":
                    key += "_output"
                if key in shape_dict:
                    attrs["label"] = "x".join(
                        str(x) for x in shape_dict[key][1:])
            dot.edge(tail_name=name, head_name=input_name, **attrs)
    return dot
