"""Fused BN+ReLU+1x1-conv block with a one-pass Pallas backward (TPU).

The cuDNN-tier custom-kernel layer of the framework (reference analog:
src/operator/nn/cudnn/cudnn_convolution-inl.h + the fused
CuDNNBatchNorm/activation kernels): ResNet bottlenecks chain
``y = conv1x1(relu(batchnorm(u)))`` where the relu activation is private
to the conv.  XLA's conv emitters run this backward as two passes over
the big tensors (a dx fusion with the BN/relu epilogue + a separate dW
fusion).  The Pallas kernel below computes, in ONE stream over
(dy, u):

    d_act   = dy @ W^T
    d_bnout = d_act * (bnout > 0)      (streamed out, bf16)
    dW      = relu(bnout)^T @ dy       (f32 accumulator)
    s1      = sum_rows d_bnout         (BN backward reduction)
    s2      = sum_rows d_bnout * xhat  (BN backward reduction)

so the weight gradient and both BatchNorm backward reductions ride the
same HBM read the data gradient needs.  The BN input gradient
``du = g*inv-scale * (d_bnout - s1/n - xhat*s2/n)`` is pass-2
elementwise work that XLA fuses into the upstream conv's backward, the
same way it fuses the eager path today.

Channel-last only (NHWC: the [N*H*W, C] matmul views are free);
off-TPU the same math runs as plain jnp, so CPU-mesh tests exercise
identical numerics.
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

_INTERPRET = False  # tests may flip for kernel-path coverage on CPU


def _on_tpu():
    # the axon tunnel registers its plugin under the "tpu" backend name
    # even when JAX_PLATFORMS=cpu selects the CPU client, so probe the
    # actual default device, not jax.default_backend()
    try:
        return jax.local_devices()[0].platform == "tpu"
    except Exception:
        return False


def enabled():
    """Is the fused block used by model code for the program being
    traced?  Decision order: the ``pallas_bnreluconv`` autotune
    variant (``stock`` = unfused layer path, ``jnp``/``pallas`` = the
    fused op with that backward) — a tuner ``force`` scope, the
    MXNET_BNRELUCONV_VARIANT hand override, or a cached per-shape
    winner applied by the jit entry points' ``program_scope`` — then
    the legacy MXNET_FUSED_BNRELUCONV env (1 = fused), default OFF.

    The r05 isolation-win/in-step-loss gap (the kernel won the 0.48 vs
    1.18 ms microbench yet lost the step 54.8 vs 46.3 ms to relayout
    copies at the custom-call boundary) is exactly why all THREE arms
    — stock, fused-jnp, fused-pallas — are separate in-step autotune
    entries now: the per-shape call is whatever autotune.json's
    measured winner says for this program signature, not a docstring.

    Read at TRACE time: a hybridized block bakes the choice into its
    cached program, so flipping the env var after the first call does
    not retrace (same as every env-config knob read inside traced
    code).  Toggle before building/hybridizing the net."""
    from ..autotune import variant_choice

    choice = variant_choice("pallas_bnreluconv")
    if choice in ("jnp", "pallas", True):
        return True
    if choice in ("stock", False):
        return False
    env = os.environ.get("MXNET_FUSED_BNRELUCONV")
    if env is not None:
        return env == "1"
    return False


# ------------------------------------------------------------------ bwd
def _bwd_kernel(dy_ref, u_ref, w_ref, g_ref, b_ref, mu_ref, inv_ref,
                dbn_ref, dw_ref, s1_ref, s2_ref,
                accw_ref, acc1_ref, acc2_ref, *, rows_total, block_m):
    i = pl.program_id(0)
    dy = dy_ref[:]                                  # [BM, Co] bf16
    u32 = u_ref[:].astype(jnp.float32)              # [BM, Ci]
    bnout = u32 * g_ref[:] + b_ref[:]
    act = bnout.astype(dy.dtype)                    # matches stored act
    # mask on the CAST value (the layer path casts BN output to the
    # activation dtype before relu); compare in f32 — the v5e VPU has
    # no bf16 compare, and half->f32 is exact so the kink is identical
    mask = act.astype(jnp.float32) > 0.0
    # tail guard: the last block may run past M; masked rows must not
    # contribute to dW/s1/s2 (their dbn writes are masked by pallas)
    row0 = i * block_m
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_m, 1), 0) + row0
    live = rows < rows_total
    mask = jnp.logical_and(mask, live)
    # padded tail rows hold UNSPECIFIED bits: zero every operand that
    # enters a contraction, not just one side — 0 * NaN is NaN and one
    # poisoned row would corrupt dW/s2 for the whole call
    dy = jnp.where(live, dy, jnp.zeros_like(dy))
    d_act = jax.lax.dot_general(
        dy, w_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    d_bnout32 = jnp.where(mask, d_act, 0.0)
    dbn_ref[:] = d_bnout32.astype(dbn_ref.dtype)
    relu_act = jnp.where(mask, act, jnp.zeros_like(act))
    partw = jax.lax.dot_general(
        relu_act, dy, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [Ci, Co]
    xhat = jnp.where(live, (u32 - mu_ref[:]) * inv_ref[:], 0.0)
    p1 = jnp.sum(d_bnout32, axis=0, keepdims=True)
    p2 = jnp.sum(d_bnout32 * xhat, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _():
        accw_ref[:] = partw
        acc1_ref[:] = p1
        acc2_ref[:] = p2

    @pl.when(i > 0)
    def _():
        accw_ref[:] = accw_ref[:] + partw
        acc1_ref[:] = acc1_ref[:] + p1
        acc2_ref[:] = acc2_ref[:] + p2

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dw_ref[:] = accw_ref[:]
        s1_ref[:] = acc1_ref[:]
        s2_ref[:] = acc2_ref[:]


try:  # pallas imports only where available (CPU wheels carry it too)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def _pick_block_m(M, Ci, Co, esize):
    """Largest block whose full VMEM plan (double-buffered dy/u inputs
    and dbn output, W input, dW output + accumulator) fits the 16MB/core
    budget with headroom; None = no block fits, use the jnp fallback
    (only the wide stage-4 1x1s hit this, and they are cheap).
    ``esize`` is the activation element size (2 for bf16/f16, 4 f32)."""
    budget = 13 * 1024 * 1024
    fixed = (2 * Ci * Co * esize  # W input (double-buffered)
             + 2 * Ci * Co * 4    # dW output buffers
             + Ci * Co * 4        # f32 accumulator scratch
             + 16 * 4 * (Ci + Co))
    for bm in (4096, 2048, 1024, 512, 256):
        need = (fixed
                + 2 * bm * (Co + Ci) * esize  # dy,u in (double-buffered)
                + 2 * bm * Ci * esize)        # dbn out (double-buffered)
        if need <= budget:
            return bm
    return None


def _bwd_pass1_pallas(dy, u, w2, g, b, mu, inv, interpret=None):
    M, Co = dy.shape
    Ci = u.shape[1]
    bm = _pick_block_m(M, Ci, Co, dy.dtype.itemsize)
    if bm is None:  # VMEM plan doesn't fit: wide 1x1s stay on XLA
        return _bwd_pass1_jnp(dy, u, w2, g, b, mu, inv)
    if interpret is None:
        # an explicitly chosen kernel arm off-TPU (the autotune race on
        # a CPU host) runs in interpret mode — honest, just slow
        interpret = _INTERPRET or not _target_is_tpu(dy)
    grid = ((M + bm - 1) // bm,)
    vec = lambda: pl.BlockSpec((1, Ci), lambda i: (0, 0))
    kern = partial(_bwd_kernel, rows_total=M, block_m=bm)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, Co), lambda i: (i, 0)),
            pl.BlockSpec((bm, Ci), lambda i: (i, 0)),
            pl.BlockSpec((Ci, Co), lambda i: (0, 0)),
            vec(), vec(), vec(), vec(),
        ],
        out_specs=[
            pl.BlockSpec((bm, Ci), lambda i: (i, 0)),
            pl.BlockSpec((Ci, Co), lambda i: (0, 0)),
            vec(), vec(),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, Ci), dy.dtype),
            jax.ShapeDtypeStruct((Ci, Co), jnp.float32),
            jax.ShapeDtypeStruct((1, Ci), jnp.float32),
            jax.ShapeDtypeStruct((1, Ci), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((Ci, Co), jnp.float32),
                        pltpu.VMEM((1, Ci), jnp.float32),
                        pltpu.VMEM((1, Ci), jnp.float32)],
        interpret=interpret,
    )(dy, u, w2, g, b, mu, inv)


def _bwd_pass1_jnp(dy, u, w2, g, b, mu, inv):
    """Same math, plain jnp (non-TPU backends and the parity tests)."""
    u32 = u.astype(jnp.float32)
    bnout = u32 * g + b
    act = bnout.astype(dy.dtype)
    mask = act.astype(jnp.float32) > 0.0
    d_act = jax.lax.dot_general(
        dy, w2, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    d_bnout32 = jnp.where(mask, d_act, 0.0)
    relu_act = jnp.where(mask, act, jnp.zeros_like(act))
    dw = jax.lax.dot_general(
        relu_act, dy, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    xhat = (u32 - mu) * inv
    s1 = jnp.sum(d_bnout32, axis=0, keepdims=True)
    s2 = jnp.sum(d_bnout32 * xhat, axis=0, keepdims=True)
    return d_bnout32.astype(dy.dtype), dw, s1, s2


import threading

_hint = threading.local()


def platform_of(arrs):
    """Platform of the first concrete array in ``arrs`` (None if all
    are tracers/None) — the single-sourced probe the jit entry points
    feed into ``set_trace_platform``."""
    for a in arrs:
        if a is None:
            continue
        try:
            return next(iter(a.devices())).platform
        except Exception:
            continue
    return None


def set_trace_platform(platform):
    """Trace-time hint: the platform the program being traced will run
    on ('tpu'/'cpu'/None).  jax traces are platform-agnostic, so a
    kernel-or-jnp choice inside a custom_vjp cannot see the target; the
    jit entry points (gluon's _call_cached) set this from their concrete
    argument devices before tracing."""
    prev = getattr(_hint, "platform", None)
    _hint.platform = platform
    return prev


def _target_is_tpu(x):
    """Best-effort: does the program containing ``x`` run on TPU?
    Order: concrete device of x (eager) -> trace hint (jit cache) ->
    process default device (make_train_step, bench)."""
    try:  # concrete jax.Array
        devs = x.devices() if hasattr(x, "devices") else None
        if devs:
            return all(d.platform == "tpu" for d in devs)
    except Exception:
        pass
    hint = getattr(_hint, "platform", None)
    if hint is not None:
        return hint == "tpu"
    return _on_tpu()


def _use_pallas(x):
    env = os.environ.get("MXNET_PALLAS")  # None = unset (default on)
    if env == "0":
        return False
    if not _HAVE_PALLAS:
        return False
    feasible = _target_is_tpu(x) or _INTERPRET
    if env == "1":
        # EXPLICITLY set: the user's hand override beats any cached
        # autotune winner (the same precedence MXNET_CONV_1X1_DOT gets)
        return feasible
    # autotune variant "pallas_bnreluconv": a tuner race or a cached
    # per-program winner overrides the platform heuristic (the r05
    # lesson — isolated kernel wins can be in-step losses, so the
    # kernel-vs-XLA call is owned by in-step timing where available).
    # "pallas" picks the kernel backward, "jnp"/"stock" the jnp math
    # (inside a "stock" program this vjp should never trace, but the
    # jnp pass is the right conservative answer if it does).
    from ..autotune import variant_choice

    choice = variant_choice("pallas_bnreluconv")
    if choice is not None:
        # an explicit kernel choice is feasible ANYWHERE: off-TPU the
        # pallas_call runs in interpret mode (keys carry the platform,
        # so a TPU-recorded winner never leaks onto a CPU program)
        return choice in ("pallas", True)
    return feasible


# ------------------------------------------------------------ composite
def _stats(u2):
    """fp32 batch stats over rows — delegates to ops/nn.py _bn_stats
    (axis=1 on the [M, Ci] view) so the fused path can never diverge
    from the BatchNorm layer's numerics policy."""
    from .nn import _bn_stats

    return _bn_stats(u2, 1)


def _fwd_math(u2, gamma, beta, w2, eps, fix_gamma):
    mean, var = _stats(u2)
    inv = jax.lax.rsqrt(var + eps)
    g32 = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
    scale = inv * g32
    shift = beta.astype(jnp.float32) - mean * scale
    u32 = u2.astype(jnp.float32)
    # cast THEN relu, matching the BatchNorm-layer + Activation path
    act = jnp.maximum((u32 * scale + shift).astype(u2.dtype),
                      jnp.zeros((), u2.dtype))
    # w2 arrives as [Ci, Co]: contract act's channel dim with dim 0
    y = jax.lax.dot_general(
        act, w2, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=u2.dtype)
    return y, mean, var, inv, scale, shift


@partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_relu_conv1x1_flat(u2, gamma, beta, w2, eps, fix_gamma):
    y, mean, var, _, _, _ = _fwd_math(u2, gamma, beta, w2, eps, fix_gamma)
    return y, mean, var


def _brc_fwd(u2, gamma, beta, w2, eps, fix_gamma):
    y, mean, var, inv, scale, shift = _fwd_math(
        u2, gamma, beta, w2, eps, fix_gamma)
    return (y, mean, var), (u2, gamma, w2, mean, inv, scale, shift)


def _brc_bwd(eps, fix_gamma, res, cts):
    u2, gamma, w2, mean, inv, scale, shift = res
    dy, dmean_ct, dvar_ct = cts
    M = u2.shape[0]
    g = scale.reshape(1, -1)
    b = shift.reshape(1, -1)
    mu = mean.reshape(1, -1)
    iv = inv.reshape(1, -1)
    pass1 = _bwd_pass1_pallas if _use_pallas(dy) else _bwd_pass1_jnp
    d_bnout, dw, s1, s2 = pass1(dy, u2, w2, g, b, mu, iv)
    s1 = s1.reshape(-1)
    s2 = s2.reshape(-1)
    # pass 2: elementwise BN input gradient (XLA fuses this into the
    # upstream backward, same as the eager _bn_train_bwd path)
    u32 = u2.astype(jnp.float32)
    xhat = (u32 - mu) * iv
    du32 = g * (d_bnout.astype(jnp.float32)
                - (s1 / M).reshape(1, -1)
                - xhat * (s2 / M).reshape(1, -1))
    if dmean_ct is not None:
        du32 = du32 + (dmean_ct / M).reshape(1, -1)
    if dvar_ct is not None:
        du32 = du32 + (dvar_ct * 2.0 / M).reshape(1, -1) * (u32 - mu)
    dgamma = jnp.zeros_like(gamma) if fix_gamma \
        else (s2 * 1.0).astype(gamma.dtype)
    dbeta = s1.astype(gamma.dtype)
    # dw computed on bf16 act/dy with f32 accumulate; cast to the
    # weight's dtype (f32 master weights keep the f32 value)
    return du32.astype(u2.dtype), dgamma, dbeta, dw.astype(w2.dtype)


_bn_relu_conv1x1_flat.defvjp(_brc_fwd, _brc_bwd)


def fused_bn_relu_conv1x1(u, gamma, beta, weight, *, eps=1e-5,
                          fix_gamma=False):
    """``conv1x1(relu(batchnorm(u)))`` with batch stats, channel-last.

    u: [N, *spatial, Ci]; weight: [Co, *(1,)*nd, Ci] (the channel-last
    O*kI convention of ops/conv.py).  Returns (y [N, *sp, Co],
    batch_mean [Ci], batch_var [Ci]) — the caller folds the batch stats
    into its running averages exactly like the plain BatchNorm layer.
    """
    ci = u.shape[-1]
    co = weight.shape[0]
    lead = u.shape[:-1]
    u2 = u.reshape(-1, ci)
    w2 = weight.reshape(co, ci)
    # kernel contracts over dim 1 of BOTH sides: pass W as [Ci, Co]
    y2, mean, var = _bn_relu_conv1x1_flat(
        u2, gamma, beta, w2.T, float(eps), bool(fix_gamma))
    return y2.reshape(lead + (co,)), mean, var


from .registry import register_op  # noqa: E402


@register_op("_contrib_BNReluConv", num_outputs=3,
             platform_sensitive=True)
def _bn_relu_conv_op(u, gamma, beta, weight, *, eps=1e-5,
                     fix_gamma=False):
    """Registry wrapper so the fused block is reachable as
    ``F._contrib_BNReluConv`` from eager, jit-cached, and symbolic
    paths alike (reference analog: the fused cuDNN norm-activation-conv
    ops registered as contrib operators)."""
    return fused_bn_relu_conv1x1(u, gamma, beta, weight, eps=eps,
                                 fix_gamma=fix_gamma)
