"""Collective exchange operators — the ZeRO bucket wire as ops.

The sharded-server train step (parallel.zero, ``optimizer_sharding=
"ps"`` + ``MXNET_ZERO_STAGE``) moves gradients and parameters as flat
dtype-homogeneous buckets: one ``reduce_scatter`` per bucket on the
backward (stages 2/3), one ``all_gather`` per bucket on the forward
prefetch (stage 3) or gather-back (stages 1/2).  These ops expose that
wire standalone so the opperf harness can time the collectives at real
bucket shapes beside the fused bucket-update rows they bracket — the
launch-overhead-vs-bytes curve that picked MXNET_KVSTORE_BIGARRAY_BOUND.

The reference has no collective ops at the NNVM surface (its exchange
lives in KVStore/ps-lite, kvstore_dist.h); these are TPU-native
additions.  Each op runs over EVERY local device via ``shard_map`` on
a 1-D data mesh — on the single-device opperf smoke they degenerate to
the identity data movement (a bucket-sized copy), which is exactly the
zero-communication floor the jsonl rows document.
"""
from __future__ import annotations

from ..base import MXNetError
from .registry import register_op


def _data_mesh():
    import jax
    import numpy as onp
    from jax.sharding import Mesh

    return Mesh(onp.array(jax.devices()), ("data",))


def _check_divisible(flat, n):
    if flat.ndim != 1 or (n and flat.shape[0] % n):
        raise MXNetError(
            "collective ops take one FLAT bucket whose length divides "
            f"the device count (got shape {tuple(flat.shape)} over "
            f"{n} devices) — pad with zero.plan_buckets' padded size")


@register_op("reduce_scatter", differentiable=False)
def reduce_scatter(data):
    """Flat-bucket gradient reduce-scatter over the local data mesh
    (the stage-2/3 backward exchange): every device contributes the
    whole replicated bucket, the sum scatters, and each device keeps
    its owned shard.  Output has the input's shape with shards laid
    out row-major (``zero.shard_slice`` order): slice ``k`` holds
    ``n_devices *`` the input's slice ``k`` when inputs replicate."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel import compat_shard_map

    mesh = _data_mesh()
    n = mesh.devices.size
    _check_divisible(data, n)
    fn = compat_shard_map(
        lambda x: jax.lax.psum_scatter(x, "data", scatter_dimension=0,
                                       tiled=True),
        mesh, in_specs=P(), out_specs=P("data"))
    return fn(data)


@register_op("all_gather", differentiable=False)
def all_gather(data):
    """Flat-bucket parameter all-gather over the local data mesh (the
    stage-3 forward prefetch / stage-1-2 gather-back): the input is
    the full flat bucket in row-major shard order, each device holds
    its shard, and every device reassembles the whole bucket (tiled,
    matching ``zero.gather_bucket``).  Identity by value — what it
    times is the wire."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel import compat_shard_map

    mesh = _data_mesh()
    n = mesh.devices.size
    _check_divisible(data, n)
    fn = compat_shard_map(
        lambda x: jax.lax.all_gather(x, "data", tiled=True),
        mesh, in_specs=P("data"), out_specs=P())
    return fn(data)
