"""Convolution / Deconvolution / Pooling / UpSampling.

Reference parity: src/operator/nn/convolution.cc, deconvolution.cc,
pooling.cc, upsampling.cc (+ their cuDNN wrappers nn/cudnn/ with the
autotuned algo registry cudnn_algoreg-inl.h).  TPU-native: one
``lax.conv_general_dilated`` call — XLA picks MXU tilings, so the whole
cuDNN algorithm-selection machinery disappears.

Layouts: the reference's channel-first NCW/NCHW/NCDHW family (weights
OIHW: num_filter, C/group, *k) and the channel-last NWC/NHWC/NDHWC
family (weights O*kI: num_filter, *k, C/group — the reference's NHWC
weight convention, convolution.cc layout param).  Channel-last is the
TPU-native layout: the channel dim lands on the 128-lane minor axis, so
XLA feeds the MXU without inserting transposes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

_CHANNEL_LAST = frozenset(("NWC", "NHWC", "NDHWC"))
_CHANNEL_FIRST = frozenset(("NCW", "NCHW", "NCDHW"))


def _tup(v, n, default=1):
    if v is None or v == ():
        return (default,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _channel_last(layout, nd):
    if layout is None or layout in _CHANNEL_FIRST:
        return False
    if layout in _CHANNEL_LAST:
        return True
    raise ValueError(f"unsupported layout {layout!r} for {nd}d conv/pool")


def _dimnums(nd, channel_last=False):
    spatial = ["W", "HW", "DHW"][nd - 1]
    if channel_last:
        specs = (f"N{spatial}C", f"O{spatial}I", f"N{spatial}C")
    else:
        specs = (f"NC{spatial}", f"OI{spatial}", f"NC{spatial}")
    return jax.lax.conv_dimension_numbers(
        (1, 1) + (1,) * nd, (1, 1) + (1,) * nd, specs)


# NOTE on 1x1 conv gradients (r04 measurement): a custom matmul-form VJP
# (lax.dot_general for dw/dx) was tried and REVERTED — isolated, every
# formulation (builtin conv transpose rule, explicit dots) runs at the
# same ~48 TF/s on v5e because these grads are BANDWIDTH-bound at
# ResNet shapes, and inside the full train step the dot form was a net
# loss (it breaks the BN-reduce/relu fusions XLA builds around the
# backward convs).
#
# r05 revisits this for CHANNEL-LAST only: in NHWC a 1x1 conv is a
# native [N*H*W, Ci] @ [Ci, Co] matmul with no layout change, and XLA's
# matmul emitters fuse elementwise epilogues at least as well as the
# conv emitters.  Gated off by default pending the step-level A/B
# (MXNET_CONV_1X1_DOT=1 to enable).


def _conv1x1_dot(data, weight, stride, cl):
    """Channel-last 1x1 conv as a dot_general over the channel dim.
    data [N, *sp, Ci], weight [Co, *(1,)*nd, Ci] -> [N, *sp', Co].

    The lowering choice is an autotune variant ("conv1x1_dot"): the
    in-step tuner forces it while racing, a cached winner applies via
    the jit entry points' program_scope, and an explicitly-set
    MXNET_CONV_1X1_DOT overrides both (autotune.variant_choice)."""
    from ..autotune import variant_choice

    if not cl or not variant_choice("conv1x1_dot", default=False):
        return None
    nd = data.ndim - 2
    if any(s != 1 for s in stride):
        idx = (slice(None),) + tuple(
            slice(None, None, s) for s in stride) + (slice(None),)
        data = data[idx]
    co = weight.shape[0]
    w2 = weight.reshape(co, data.shape[-1])
    return jax.lax.dot_general(
        data, w2, dimension_numbers=(((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=data.dtype)


def _stem_space_to_depth(data, weight, jnp_pad=jnp.pad):
    """The 7x7/stride-2/pad-3 RGB stem conv as a 4x4/stride-1 conv on a
    space-to-depth input (channel-first only).

    A 3-channel 7x7 kernel occupies 3 of the MXU's 128 input lanes; the
    2x2 space-to-depth rearrangement quadruples the channel count and
    halves the spatial extent, which is the standard TPU ResNet stem
    transform (MLPerf reference models use the same trick).  Exactly
    equivalent: with xp = pad(x, 3) and k = 2a+b (b the parity),
    y[p] = sum_k w[k] xp[2p+k] = sum_b sum_a w[2a+b] xp_b[p+a].
    Autodiff flows through the rearrangement, so backward convs also run
    on the 12-channel tensors.
    """
    n, c, h, w_ = data.shape
    o = weight.shape[0]
    xp = jnp_pad(data, ((0, 0), (0, 0), (3, 3), (3, 3)))
    hq, wq = (h + 6) // 2, (w_ + 6) // 2
    xs = xp.reshape(n, c, hq, 2, wq, 2)
    xs = xs.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * 4, hq, wq)
    w8 = jnp_pad(weight, ((0, 0), (0, 0), (0, 1), (0, 1)))
    ws = w8.reshape(o, c, 4, 2, 4, 2)
    ws = ws.transpose(0, 1, 3, 5, 2, 4).reshape(o, c * 4, 4, 4)
    return jax.lax.conv_general_dilated(
        xs, ws, window_strides=(1, 1), padding=[(0, 0), (0, 0)],
        dimension_numbers=_dimnums(2, False), feature_group_count=1)


@register_op("Convolution", aliases=("Convolution_v1",))
def convolution(data, weight, bias=None, *, kernel, num_filter, stride=None,
                dilate=None, pad=None, num_group=1, no_bias=False,
                workspace=1024, cudnn_tune=None, cudnn_off=False,
                layout=None):
    """Reference: src/operator/nn/convolution.cc."""
    nd = len(kernel)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd, 0)
    cl = _channel_last(layout, nd)
    if (nd == 2 and not cl and kernel == (7, 7) and stride == (2, 2)
            and pad == (3, 3) and dilate == (1, 1) and num_group == 1
            and data.shape[1] <= 4 and data.shape[2] % 2 == 0
            and data.shape[3] % 2 == 0):
        out = _stem_space_to_depth(data, weight)
    elif (kernel == (1,) * nd and pad == (0,) * nd
          and dilate == (1,) * nd and num_group == 1
          and (out := _conv1x1_dot(data, weight, stride, cl)) is not None):
        pass  # NHWC 1x1 fast path (see _conv1x1_dot)
    else:
        dn = _dimnums(nd, cl)
        out = jax.lax.conv_general_dilated(
            data, weight,
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=dn,
            feature_group_count=num_group,
        )
    if not no_bias and bias is not None:
        out = out + (bias if cl else bias.reshape((1, -1) + (1,) * nd))
    return out


@register_op("Deconvolution")
def deconvolution(data, weight, bias=None, *, kernel, num_filter,
                  stride=None, dilate=None, pad=None, adj=None,
                  target_shape=None, num_group=1, no_bias=True,
                  workspace=512, cudnn_tune=None, cudnn_off=False,
                  layout=None):
    """Reference: src/operator/nn/deconvolution.cc — the transposed conv:
    implemented as input-dilated convolution (lhs_dilation=stride)."""
    nd = len(kernel)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd, 0)
    adj = _tup(adj, nd, 0)
    cl = _channel_last(layout, nd)
    # effective padding for transposed conv: k_eff - 1 - p
    padding = []
    for i in range(nd):
        k_eff = (kernel[i] - 1) * dilate[i] + 1
        lo = k_eff - 1 - pad[i]
        hi = k_eff - 1 - pad[i] + adj[i]
        padding.append((lo, hi))
    if cl:
        # weight (C_in, *k, C_out/group) -> flip spatial; kernel IO roles
        # are expressed via the I<spatial>O rhs spec, no physical swap
        spatial = ["W", "HW", "DHW"][nd - 1]
        dn = jax.lax.conv_dimension_numbers(
            (1, 1) + (1,) * nd, (1, 1) + (1,) * nd,
            (f"N{spatial}C", f"I{spatial}O", f"N{spatial}C"))
        w = jnp.flip(weight, axis=tuple(range(1, 1 + nd)))
        if num_group > 1:
            ci, co_g = w.shape[0], w.shape[-1]
            w = w.reshape(num_group, ci // num_group, *w.shape[1:])
            w = jnp.moveaxis(w, 0, -2)  # (ci/g, *k, g, co_g)
            w = w.reshape(ci // num_group, *w.shape[1:-2], num_group * co_g)
    else:
        dn = _dimnums(nd)
        # weight layout (C_in, C_out/group, *k) -> flip spatial, swap IO
        w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
        if num_group > 1:
            ci, co_g = w.shape[0], w.shape[1]
            w = w.reshape(num_group, ci // num_group, co_g, *w.shape[2:])
            w = jnp.swapaxes(w, 1, 2)
            w = w.reshape(num_group * co_g, ci // num_group, *w.shape[3:])
        else:
            w = jnp.swapaxes(w, 0, 1)
    out = jax.lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if not no_bias and bias is not None:
        out = out + (bias if cl else bias.reshape((1, -1) + (1,) * nd))
    return out


@register_op("Pooling", aliases=("Pooling_v1",))
def pooling(data, *, kernel=(), pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            count_include_pad=True, cudnn_off=False, p_value=2,
            layout=None):
    """Reference: src/operator/nn/pooling.cc via lax.reduce_window."""
    nd = data.ndim - 2
    cl = _channel_last(layout, nd)
    sp0 = 1 if cl else 2  # first spatial axis
    if global_pool:
        kernel = data.shape[sp0:sp0 + nd]
        stride = (1,) * nd
        pad = (0,) * nd
    stride = _tup(stride, nd)
    pad = _tup(pad, nd, 0)
    kernel = _tup(kernel, nd)
    if cl:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
    sp_pad = [(p, p) for p in pad]
    if pooling_convention == "full":
        # ceil mode: add extra right-pad so last window fits
        sp_pad = []
        for i in range(nd):
            size = data.shape[sp0 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            extra = (stride[i] - rem) % stride[i] if rem else 0
            sp_pad.append((pad[i], pad[i] + extra))
    if cl:
        base_pad = [(0, 0)] + sp_pad + [(0, 0)]
    else:
        base_pad = [(0, 0), (0, 0)] + sp_pad

    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(data, init, jax.lax.max, dims, strides,
                                     base_pad)
    if pool_type in ("avg", "sum"):
        s = jax.lax.reduce_window(data, 0.0, jax.lax.add, dims, strides,
                                  base_pad)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones_like(data)
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides,
                                    base_pad)
        return s / cnt
    if pool_type == "lp":
        s = jax.lax.reduce_window(jnp.abs(data) ** p_value, 0.0, jax.lax.add,
                                  dims, strides, base_pad)
        return s ** (1.0 / p_value)
    raise ValueError(f"unknown pool_type {pool_type}")


@register_op("UpSampling")
def upsampling(*inputs, scale, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    """Reference: src/operator/nn/upsampling.cc."""
    data = inputs[0]
    n, c, h, w = data.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    else:  # bilinear: reference uses a Deconvolution with bilinear kernel
        out = jax.image.resize(data, (n, c, h * scale, w * scale),
                               method="bilinear")
    return out


@register_op("BilinearSampler")
def bilinear_sampler(data, grid, *, cudnn_off=False):
    """Reference: src/operator/bilinear_sampler.cc — grid in [-1, 1]."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2
    gy = (grid[:, 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(y, x):
        yc = jnp.clip(y, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(x, 0, w - 1).astype(jnp.int32)
        valid = ((y >= 0) & (y <= h - 1) & (x >= 0) & (x <= w - 1))
        idx = yc * w + xc
        flat = data.reshape(n, c, h * w)
        g = jnp.take_along_axis(
            flat, idx.reshape(n, 1, -1).repeat(c, axis=1), axis=2
        ).reshape(n, c, *gx.shape[1:])
        return g * valid[:, None].astype(data.dtype)

    out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[:, None]
           + gather(y0, x0 + 1) * (wx * (1 - wy))[:, None]
           + gather(y0 + 1, x0) * ((1 - wx) * wy)[:, None]
           + gather(y0 + 1, x0 + 1) * (wx * wy)[:, None])
    return out


@register_op("GridGenerator")
def grid_generator(data, *, transform_type="affine", target_shape=(0, 0)):
    """Reference: src/operator/grid_generator.cc."""
    h, w = target_shape
    if transform_type == "affine":
        n = data.shape[0]
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx.reshape(-1), gy.reshape(-1),
                          jnp.ones(h * w)], axis=0)
        theta = data.reshape(n, 2, 3)
        out = jnp.einsum("nij,jk->nik", theta, base)
        return out.reshape(n, 2, h, w)
    # warp
    n = data.shape[0]
    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    flow_x = (data[:, 0] + gx) * 2 / max(w - 1, 1) - 1
    flow_y = (data[:, 1] + gy) * 2 / max(h - 1, 1) - 1
    return jnp.stack([flow_x, flow_y], axis=1)


@register_op("SpatialTransformer")
def spatial_transformer(data, loc, *, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=False):
    """Reference: src/operator/spatial_transformer.cc."""
    from .registry import get_op

    g = get_op("GridGenerator").fn(loc, transform_type=transform_type,
                                   target_shape=target_shape)
    return get_op("BilinearSampler").fn(data, g)
