"""Flash attention — Pallas TPU kernel with online softmax.

SURVEY.md §5.7 mandate: the reference has no fused attention (only
bucketing + contrib div_sqrt_dim, src/operator/contrib/transformer.cc);
long-context on TPU requires an O(seq) -memory attention kernel.  This
is the single-chip building block; ring context parallelism composes it
across chips (mxnet_tpu.parallel.ring).

Design (standard flash-attention-2 schedule on the MXU):
  grid = (batch*heads, q_blocks); the kernel walks k/v blocks in VMEM,
  keeping the running max m, normalizer l and accumulator acc in f32
  scratch; one rescale per block keeps everything numerically exact.
Backward: recomputation in query chunks — each chunk re-derives its
attention rows (O(chunk * seq) live memory, not O(seq^2)) and
contributes dq directly while dk/dv accumulate across chunks.
Causal masking uses bottom-right alignment (query i attends keys
j <= i + seq_k - seq_q), identical across kernel/fallback/backward.

Falls back to a fused jnp implementation off-TPU or for shapes that
don't tile (seq % block != 0) — same math, same vjp.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register_op

_BLOCK_Q = 128
_BLOCK_K = 128


def _naive_attention(q, k, v, causal, sm_scale):
    """Reference math in fp32: softmax(q k^T * scale [+ mask]) v."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), klen - qlen)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, sm_scale,
                  block_k, seq_k):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)  # (block_q, d)
    block_q = q.shape[0]
    qi = pl.program_id(1)
    seq_q = pl.num_programs(1) * block_q
    # bottom-right causal alignment: shift query positions by sk - sq
    q_off = qi * block_q + (seq_k - seq_q)

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k),
                      :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k),
                      :].astype(jnp.float32)
        s = q @ k_blk.T * sm_scale  # (block_q, block_k)
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # guard fully-masked rows: exp(-inf - -inf) -> use safe max
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + p @ v_blk
        return m_new, l, acc

    if causal:
        # skip key blocks entirely above the diagonal
        last_kb = jnp.minimum((q_off + block_q + block_k - 1) // block_k,
                              num_kb)
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m, l, acc))
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)


def _flash_forward_pallas(q, k, v, causal, sm_scale, block_q=_BLOCK_Q,
                          block_k=_BLOCK_K, interpret=False):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, sq, d)
    k3 = k.reshape(bh, sk, d)
    v3 = v.reshape(bh, sk, d)
    grid = (bh, sq // block_q)
    kernel = functools.partial(_flash_kernel, causal=causal,
                               sm_scale=sm_scale, block_k=block_k,
                               seq_k=sk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b_, i: (b_, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b_, i: (b_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, sq, d)


def _can_use_pallas(q, k, block_q, block_k):
    sq, sk = q.shape[2], k.shape[2]
    if sq % block_q or sk % block_k:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _tiles(q, k, block_q=_BLOCK_Q, block_k=_BLOCK_K):
    return q.shape[2] % block_q == 0 and k.shape[2] % block_k == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, sm_scale, interpret):
    if _tiles(q, k) and (interpret or _can_use_pallas(q, k, _BLOCK_Q,
                                                      _BLOCK_K)):
        return _flash_forward_pallas(q, k, v, causal, sm_scale,
                                     interpret=interpret)
    return _naive_attention(q, k, v, causal, sm_scale)


def _flash_fwd(q, k, v, causal, sm_scale, interpret):
    return _flash(q, k, v, causal, sm_scale, interpret), (q, k, v)


_BWD_CHUNK = 512


def _flash_bwd(causal, sm_scale, interpret, res, g):
    # recompute in query chunks: O(chunk * seq_k) live attention rows
    # instead of the full O(seq^2) matrix
    q, k, v = res
    sq = q.shape[2]
    chunk = min(_BWD_CHUNK, sq)
    if sq % chunk:
        chunk = sq  # ragged: single chunk (still correct)
    nchunks = sq // chunk
    sk = k.shape[2]

    def chunk_attn(q_c, k_, v_, off):
        s = jnp.einsum("bhqd,bhkd->bhqk", q_c.astype(jnp.float32),
                       k_.astype(jnp.float32)) * sm_scale
        if causal:
            qpos = off + jnp.arange(chunk) + (sk - sq)
            kpos = jnp.arange(sk)
            s = jnp.where((qpos[:, None] >= kpos[None, :])[None, None],
                          s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v_.astype(jnp.float32)).astype(q_c.dtype)

    dq = jnp.zeros_like(q)
    dk = jnp.zeros_like(k, shape=k.shape).astype(jnp.float32)
    dv = jnp.zeros_like(v, shape=v.shape).astype(jnp.float32)
    for ci in range(nchunks):
        off = ci * chunk
        q_c = jax.lax.dynamic_slice_in_dim(q, off, chunk, axis=2)
        g_c = jax.lax.dynamic_slice_in_dim(g, off, chunk, axis=2)
        _, vjp = jax.vjp(
            lambda q_, k_, v_, off=off: chunk_attn(q_, k_, v_, off),
            q_c, k, v)
        dq_c, dk_c, dv_c = vjp(g_c)
        dq = jax.lax.dynamic_update_slice_in_dim(dq, dq_c, off, axis=2)
        dk = dk + dk_c.astype(jnp.float32)
        dv = dv + dv_c.astype(jnp.float32)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    interpret=False):
    """Fused attention over (batch, heads, seq, head_dim) operands."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash(q, k, v, causal, float(sm_scale), interpret)


@register_op("_contrib_dot_product_attention",
             aliases=("dot_product_attention",))
def dot_product_attention(q, k, v, *, num_heads=1, causal=False,
                          sm_scale=None, interpret=False):
    """Multi-head attention over (batch, seq, num_heads*head_dim)
    inputs, flash-backed (the modern replacement for the reference's
    contrib attention helpers)."""
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // num_heads

    def split(x, s):
        return x.reshape(b, s, num_heads, d).transpose(0, 2, 1, 3)

    out = flash_attention(split(q, sq), split(k, sk), split(v, sk),
                          causal=causal, sm_scale=sm_scale,
                          interpret=interpret)
    return out.transpose(0, 2, 1, 3).reshape(b, sq, hd)


@register_op("_contrib_div_sqrt_dim")
def div_sqrt_dim(data):
    """Reference: src/operator/contrib/transformer.cc:33-40."""
    return data / (data.shape[-1] ** 0.5)
