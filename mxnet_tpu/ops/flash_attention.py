"""Flash attention — Pallas TPU kernel with online softmax.

SURVEY.md §5.7 mandate: the reference has no fused attention (only
bucketing + contrib div_sqrt_dim, src/operator/contrib/transformer.cc);
long-context on TPU requires an O(seq) -memory attention kernel.  This
is the single-chip building block; ring context parallelism composes it
across chips (mxnet_tpu.parallel.ring).

Design (standard flash-attention-2 schedule on the MXU):
  grid = (batch*heads, q_blocks); the kernel walks k/v blocks in VMEM,
  keeping the running max m, normalizer l and accumulator acc in f32
  scratch; one rescale per block keeps everything numerically exact.
Backward: recomputation in query chunks — each chunk re-derives its
attention rows (O(chunk * seq) live memory, not O(seq^2)) and
contributes dq directly while dk/dv accumulate across chunks.
Causal masking uses bottom-right alignment (query i attends keys
j <= i + seq_k - seq_q), identical across kernel/fallback/backward.

Round 14 — the kernel is an in-step autotune variant: the
``flash_attention`` op in ``autotune.VARIANT_OPS`` races the naive
fused-jnp math against the Pallas schedule (block-size sub-variants
included) inside the caller's real jitted step, and the winner applies
per (shape, dtype, platform, mesh) at trace time.  Variants:

* ``naive``       — the fused jnp math (XLA's own fusion);
* ``pallas``      — the kernel at the default 128/128 q/k blocks;
* ``pallas_b256`` — 256/256 blocks (wins on long-seq shapes where the
  larger q tile amortizes the k/v stream);
* ``pallas_pad``  — tile-align by PADDING: non-aligned seq lens pad up
  to the block size, padded keys are masked out of the softmax
  (``kv_valid``), padded query rows are sliced off — so shapes that
  used to silently fall back to jnp can still race the kernel.

Falls back to the fused jnp implementation off-TPU or for shapes that
don't tile (seq % block != 0) — same math, same vjp.  The silent part
of that fallback is gone: a shape that WANTED the kernel but could not
tile emits an ``autotune`` telemetry event naming the reason, so a
run log shows exactly which attention shapes never raced.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register_op

_BLOCK_Q = 128
_BLOCK_K = 128

#: forced-value -> (block_q, block_k) for the kernel sub-variants
_VARIANT_BLOCKS = {
    "pallas": (_BLOCK_Q, _BLOCK_K),
    "pallas_b256": (256, 256),
    "pallas_pad": (_BLOCK_Q, _BLOCK_K),
}


def _naive_attention(q, k, v, causal, sm_scale, kv_valid=None,
                     q_valid=None):
    """Reference math in fp32: softmax(q k^T * scale [+ mask]) v.
    ``kv_valid``/``q_valid`` are the padding-shim contract: keys at
    positions >= kv_valid are masked out, and the causal alignment is
    computed against the VALID lengths so padding never shifts which
    real keys a real query sees."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    qlen, klen = s.shape[-2], s.shape[-1]
    if causal:
        eff_k = klen if kv_valid is None else kv_valid
        eff_q = qlen if q_valid is None else q_valid
        mask = jnp.tril(jnp.ones((qlen, klen), bool), eff_k - eff_q)
        s = jnp.where(mask, s, -jnp.inf)
    if kv_valid is not None and kv_valid < klen:
        kmask = (jnp.arange(klen) < kv_valid)[None, None, None, :]
        s = jnp.where(kmask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    if kv_valid is not None and kv_valid < klen:
        # a fully-masked row softmaxes to uniform garbage; zero it the
        # way the kernel's l=0 guard does
        p = jnp.where(jnp.isfinite(s).any(-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, sm_scale,
                  block_k, seq_k, kv_valid, q_valid):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)  # (block_q, d)
    block_q = q.shape[0]
    qi = pl.program_id(1)
    seq_q = pl.num_programs(1) * block_q
    # bottom-right causal alignment: shift query positions by sk - sq
    # computed against the VALID lengths when the padding shim
    # appended masked keys / sliced-off queries
    eff_k = seq_k if kv_valid is None else kv_valid
    eff_q = seq_q if q_valid is None else q_valid
    q_off = qi * block_q + (eff_k - eff_q)

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k),
                      :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k),
                      :].astype(jnp.float32)
        s = q @ k_blk.T * sm_scale  # (block_q, block_k)
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        if kv_valid is not None and kv_valid < seq_k:
            # padding shim: keys past the true length never score
            s = jnp.where(kpos < kv_valid, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # guard fully-masked rows: exp(-inf - -inf) -> use safe max
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + p @ v_blk
        return m_new, l, acc

    last_kb = num_kb
    if kv_valid is not None and kv_valid < seq_k:
        # the tail blocks past the true key length are fully masked
        last_kb = (kv_valid + block_k - 1) // block_k
    if causal:
        # skip key blocks entirely above the diagonal
        last_kb = jnp.minimum((q_off + block_q + block_k - 1) // block_k,
                              last_kb)
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m, l, acc))
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)


def _flash_forward_pallas(q, k, v, causal, sm_scale, block_q=_BLOCK_Q,
                          block_k=_BLOCK_K, kv_valid=None,
                          q_valid=None, interpret=False):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, sq, d)
    k3 = k.reshape(bh, sk, d)
    v3 = v.reshape(bh, sk, d)
    grid = (bh, sq // block_q)
    kernel = functools.partial(_flash_kernel, causal=causal,
                               sm_scale=sm_scale, block_k=block_k,
                               seq_k=sk, kv_valid=kv_valid,
                               q_valid=q_valid)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b_, i: (b_, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b_, i: (b_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, sq, d)


_FALLBACK_SEEN = set()


def _fallback_event(reason, q, k, block_q, block_k):
    """A shape that wanted the kernel but fell back to the fused jnp
    math: emit an ``autotune`` run-log event naming the reason (the
    silent half of _can_use_pallas, now attributed).  Deduped per
    (shapes, blocks) — an eager predict loop re-executes this per
    call, and N identical records explain nothing the first did not."""
    dedup = (tuple(q.shape), tuple(k.shape), block_q, block_k)
    if dedup in _FALLBACK_SEEN:
        return
    try:
        from .. import telemetry

        if telemetry.current() is None:
            return  # unarmed: nothing recorded, don't latch the dedup
        telemetry.event(
            "autotune", op="flash_attention", winner="naive",
            cached=False, reason=str(reason),
            shape=str((tuple(q.shape), tuple(k.shape))),
            blocks=f"{block_q}x{block_k}")
        _FALLBACK_SEEN.add(dedup)
    except Exception:
        pass  # telemetry must never kill a trace


def _on_tpu_target():
    from .pallas_conv import _on_tpu  # ONE backend probe for all three
    #                                   kernel families (ops package
    #                                   import order: probe lazily)

    return _on_tpu()


def _can_use_pallas(q, k, block_q, block_k):
    """Feasibility of the kernel for this shape+platform.  No longer a
    silent gate: a tile-alignment miss emits a telemetry event naming
    the reason (and the ``pallas_pad`` variant exists exactly so these
    shapes can still race aligned-padded)."""
    sq, sk = q.shape[2], k.shape[2]
    if sq % block_q or sk % block_k:
        _fallback_event(
            f"seq not tile-aligned (seq_q {sq} % {block_q} = "
            f"{sq % block_q}, seq_k {sk} % {block_k} = {sk % block_k});"
            " the pallas_pad variant can race this shape padded",
            q, k, block_q, block_k)
        return False
    return _on_tpu_target()


def _tiles(q, k, block_q=_BLOCK_Q, block_k=_BLOCK_K):
    return q.shape[2] % block_q == 0 and k.shape[2] % block_k == 0


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, sm_scale, interpret, variant, kv_valid,
           q_valid):
    if variant == "naive":
        return _naive_attention(q, k, v, causal, sm_scale,
                                kv_valid=kv_valid, q_valid=q_valid)
    if variant in _VARIANT_BLOCKS:
        bq, bk = _VARIANT_BLOCKS[variant]
        if not _tiles(q, k, bq, bk):
            _fallback_event(
                f"forced variant {variant!r} cannot tile "
                f"(seq_q {q.shape[2]}, seq_k {k.shape[2]})",
                q, k, bq, bk)
            return _naive_attention(q, k, v, causal, sm_scale,
                                    kv_valid=kv_valid, q_valid=q_valid)
        # an explicitly chosen kernel variant runs the kernel even
        # off-TPU (interpret mode): the race stays honest on any host
        return _flash_forward_pallas(
            q, k, v, causal, sm_scale, block_q=bq, block_k=bk,
            kv_valid=kv_valid, q_valid=q_valid,
            interpret=interpret or not _on_tpu_target())
    # default heuristic (no variant decision): kernel on TPU where the
    # shape tiles, fused jnp otherwise — _can_use_pallas emits the
    # attributed fallback event on a tile-alignment miss
    if (interpret and _tiles(q, k)) or \
            _can_use_pallas(q, k, _BLOCK_Q, _BLOCK_K):
        return _flash_forward_pallas(q, k, v, causal, sm_scale,
                                     kv_valid=kv_valid,
                                     q_valid=q_valid,
                                     interpret=interpret)
    return _naive_attention(q, k, v, causal, sm_scale,
                            kv_valid=kv_valid, q_valid=q_valid)


def _flash_fwd(q, k, v, causal, sm_scale, interpret, variant, kv_valid,
               q_valid):
    return (_flash(q, k, v, causal, sm_scale, interpret, variant,
                   kv_valid, q_valid), (q, k, v))


_BWD_CHUNK = 512


def _flash_bwd(causal, sm_scale, interpret, variant, kv_valid, q_valid,
               res, g):
    # recompute in query chunks: O(chunk * seq_k) live attention rows
    # instead of the full O(seq^2) matrix
    q, k, v = res
    sq = q.shape[2]
    chunk = min(_BWD_CHUNK, sq)
    if sq % chunk:
        chunk = sq  # ragged: single chunk (still correct)
    nchunks = sq // chunk
    sk = k.shape[2]

    def chunk_attn(q_c, k_, v_, off):
        s = jnp.einsum("bhqd,bhkd->bhqk", q_c.astype(jnp.float32),
                       k_.astype(jnp.float32)) * sm_scale
        kpos = jnp.arange(sk)
        if causal:
            eff_k = sk if kv_valid is None else kv_valid
            eff_q = sq if q_valid is None else q_valid
            qpos = off + jnp.arange(chunk) + (eff_k - eff_q)
            s = jnp.where((qpos[:, None] >= kpos[None, :])[None, None],
                          s, -jnp.inf)
        if kv_valid is not None and kv_valid < sk:
            s = jnp.where((kpos < kv_valid)[None, None, None], s,
                          -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        if kv_valid is not None and kv_valid < sk:
            p = jnp.where(jnp.isfinite(s).any(-1, keepdims=True), p,
                          0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v_.astype(jnp.float32)).astype(q_c.dtype)

    dq = jnp.zeros_like(q)
    dk = jnp.zeros_like(k, shape=k.shape).astype(jnp.float32)
    dv = jnp.zeros_like(v, shape=v.shape).astype(jnp.float32)
    for ci in range(nchunks):
        off = ci * chunk
        q_c = jax.lax.dynamic_slice_in_dim(q, off, chunk, axis=2)
        g_c = jax.lax.dynamic_slice_in_dim(g, off, chunk, axis=2)
        _, vjp = jax.vjp(
            lambda q_, k_, v_, off=off: chunk_attn(q_, k_, v_, off),
            q_c, k, v)
        dq_c, dk_c, dv_c = vjp(g_c)
        dq = jax.lax.dynamic_update_slice_in_dim(dq, dq_c, off, axis=2)
        dk = dk + dk_c.astype(jnp.float32)
        dv = dv + dv_c.astype(jnp.float32)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pad_to(x, axis, mult):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _resolve_variant(variant):
    """The trace-time variant decision: explicit arg > the autotune
    registry's ``flash_attention`` choice (force > env > cached
    winner) > None (the platform heuristic)."""
    if variant is not None:
        return variant
    from ..autotune import variant_choice

    return variant_choice("flash_attention")


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    interpret=False, variant=None):
    """Fused attention over (batch, heads, seq, head_dim) operands.

    ``variant`` picks the lowering explicitly (``naive`` / ``pallas``
    / ``pallas_b256`` / ``pallas_pad``); None consults the autotune
    registry (``VARIANT_OPS['flash_attention']``) and falls back to
    the platform heuristic."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    variant = _resolve_variant(variant)
    if variant == "pallas_pad":
        bq, bk = _VARIANT_BLOCKS["pallas_pad"]
        sq, sk = q.shape[2], k.shape[2]
        if sq % bq == 0 and sk % bk == 0:
            variant = "pallas"  # already aligned: no shim needed
        else:
            # pad q AND k/v up to the blocks; the kernel computes the
            # causal alignment against the VALID lengths (q_valid/
            # kv_valid), padded keys are masked out of the softmax,
            # and padded query rows are sliced off below
            qp = _pad_to(q, 2, bq)
            kp = _pad_to(k, 2, bk)
            vp = _pad_to(v, 2, bk)
            out = _flash(qp, kp, vp, causal, float(sm_scale),
                         interpret, "pallas",
                         sk if kp.shape[2] != sk else None,
                         sq if qp.shape[2] != sq else None)
            return out[:, :, :sq, :]
    return _flash(q, k, v, causal, float(sm_scale), interpret, variant,
                  None, None)


def _resolve_paged_variant(variant):
    """Trace-time decision for the decode-cache attention: explicit
    arg > the autotune registry's ``paged_decode_attention`` choice
    (force > MXNET_PAGED_ATTENTION > cached winner) > gather."""
    if variant is not None:
        return variant
    from ..autotune import variant_choice

    return variant_choice("paged_decode_attention", default="gather")


def _dequant_block(blk, scale):
    """fp32 view of a gathered KV block; ``scale`` is the int8 cache's
    per-(token, head) factor (quantization.kv contract), None = the
    block is already a float dtype."""
    if scale is None:
        return blk.astype(jnp.float32)
    return blk.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]


def paged_decode_attention(q, k_pages, v_pages, page_table, seq_lens,
                           sm_scale=None, k_scale=None, v_scale=None,
                           variant=None):
    """Single-token decode attention over a PAGED KV cache (round 17).

    The generative server's decode step calls this once per layer per
    token: each decode slot's query attends to the keys its page-table
    row maps into the physical page pool — never to another sequence's
    pages, never to unwritten tail positions.

    Operands::

      q          (slots, heads, head_dim)   one query token per slot
      k_pages    (pages, page_tokens, heads, head_dim)  physical pool
      v_pages    (pages, page_tokens, heads, head_dim)
      page_table (slots, max_pages) int32   logical -> physical pages
      seq_lens   (slots,) int32             valid tokens per slot

    ``k_scale``/``v_scale`` (pages, page_tokens, heads) mark an int8
    pool: blocks dequantize AFTER the gather (per block in the paged
    walk), so HBM holds int8 + scales only.  A slot with seq_len 0 is
    inactive: every key masks out and the output row is exactly zero —
    the same fully-masked-row guard as the flash kernel's l=0 path.

    Variants (autotune op ``paged_decode_attention``): ``gather``
    materializes the slot's K/V with one fancy-index gather then runs
    a dense masked softmax; ``paged`` walks the page list with an
    online-softmax accumulator (m/l/acc carry, one page live at a
    time) — flash-attention's schedule transposed onto the page table.
    Both are exact (no approximation), so the race is purely a speed
    decision.
    """
    slots, heads, head_dim = q.shape
    page_tokens = k_pages.shape[1]
    max_pages = page_table.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (head_dim ** 0.5)
    variant = _resolve_paged_variant(variant)
    qf = q.astype(jnp.float32)

    if variant == "paged":
        def body(i, carry):
            m, l, acc = carry
            phys = page_table[:, i]  # (slots,)
            k_blk = _dequant_block(
                k_pages[phys],
                None if k_scale is None else k_scale[phys])
            v_blk = _dequant_block(
                v_pages[phys],
                None if v_scale is None else v_scale[phys])
            s = jnp.einsum("shd,sthd->sht", qf, k_blk) * sm_scale
            pos = i * page_tokens + jnp.arange(page_tokens)
            s = jnp.where(pos[None, None, :] < seq_lens[:, None, None],
                          s, -jnp.inf)
            m_new = jnp.maximum(m, s.max(axis=-1))
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(s - m_safe[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
            l = l * alpha + p.sum(axis=-1)
            acc = acc * alpha[..., None] + \
                jnp.einsum("sht,sthd->shd", p, v_blk)
            return m_new, l, acc

        m0 = jnp.full((slots, heads), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((slots, heads), jnp.float32)
        acc0 = jnp.zeros((slots, heads, head_dim), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, max_pages, body, (m0, l0, acc0))
        return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)

    # gather: one fancy-index gather materializes (slots, total, H, D)
    k = _dequant_block(
        k_pages[page_table],
        None if k_scale is None else k_scale[page_table])
    v = _dequant_block(
        v_pages[page_table],
        None if v_scale is None else v_scale[page_table])
    total = max_pages * page_tokens
    k = k.reshape(slots, total, heads, head_dim)
    v = v.reshape(slots, total, heads, head_dim)
    s = jnp.einsum("shd,sthd->sht", qf, k) * sm_scale
    pos = jnp.arange(total)
    s = jnp.where(pos[None, None, :] < seq_lens[:, None, None], s,
                  -jnp.inf)
    m = s.max(axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = p.sum(axis=-1, keepdims=True)
    out = jnp.einsum("sht,sthd->shd", p, v) / jnp.maximum(l[..., 0],
                                                          1e-30)[..., None]
    return out.astype(q.dtype)


@register_op("_contrib_dot_product_attention",
             aliases=("dot_product_attention",))
def dot_product_attention(q, k, v, *, num_heads=1, causal=False,
                          sm_scale=None, interpret=False, variant=None):
    """Multi-head attention over (batch, seq, num_heads*head_dim)
    inputs, flash-backed (the modern replacement for the reference's
    contrib attention helpers)."""
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // num_heads

    def split(x, s):
        return x.reshape(b, s, num_heads, d).transpose(0, 2, 1, 3)

    out = flash_attention(split(q, sq), split(k, sk), split(v, sk),
                          causal=causal, sm_scale=sm_scale,
                          interpret=interpret, variant=variant)
    return out.transpose(0, 2, 1, 3).reshape(b, sq, hd)


@register_op("_contrib_div_sqrt_dim")
def div_sqrt_dim(data):
    """Reference: src/operator/contrib/transformer.cc:33-40."""
    return data / (data.shape[-1] ** 0.5)
