"""Random sampling ops.

Reference parity: src/operator/random/ (sample_op.cc: uniform/normal/gamma/
exponential/poisson/negative_binomial/generalized_negative_binomial/randint,
multinomial, shuffle) — SURVEY.md §2.3 `random/`.  TPU-native: JAX threaded
PRNG; the dispatcher injects a fresh key per call (see ops/registry.py
``key_param``), replacing the reference's per-device generator arrays
(include/mxnet/random_generator.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..dtype import normalize_dtype
from .registry import register_op


def _dt(dtype):
    return normalize_dtype(dtype if dtype not in (None, "None") else "float32")


@register_op("_random_uniform", aliases=("random_uniform", "uniform"),
             key_param="key", differentiable=False)
def random_uniform(*, low=0.0, high=1.0, shape=(1,), dtype=None, ctx=None,
                   key=None):
    return jax.random.uniform(key, tuple(shape), _dt(dtype), low, high)


@register_op("_random_normal", aliases=("random_normal", "normal"),
             key_param="key", differentiable=False)
def random_normal(*, loc=0.0, scale=1.0, shape=(1,), dtype=None, ctx=None,
                  key=None):
    return jax.random.normal(key, tuple(shape), _dt(dtype)) * scale + loc


@register_op("_random_gamma", aliases=("random_gamma",), key_param="key",
             differentiable=False)
def random_gamma(*, alpha=1.0, beta=1.0, shape=(1,), dtype=None, ctx=None,
                 key=None):
    return jax.random.gamma(key, alpha, tuple(shape), _dt(dtype)) * beta


@register_op("_random_exponential", aliases=("random_exponential",),
             key_param="key", differentiable=False)
def random_exponential(*, lam=1.0, shape=(1,), dtype=None, ctx=None,
                       key=None):
    return jax.random.exponential(key, tuple(shape), _dt(dtype)) / lam


@register_op("_random_poisson", aliases=("random_poisson",), key_param="key",
             differentiable=False)
def random_poisson(*, lam=1.0, shape=(1,), dtype=None, ctx=None, key=None):
    return jax.random.poisson(key, lam, tuple(shape)).astype(_dt(dtype))


@register_op("_random_negative_binomial",
             aliases=("random_negative_binomial",), key_param="key",
             differentiable=False)
def random_negative_binomial(*, k=1, p=1.0, shape=(1,), dtype=None, ctx=None,
                             key=None):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, tuple(shape)).astype(_dt(dtype))


@register_op("_random_generalized_negative_binomial",
             aliases=("random_generalized_negative_binomial",),
             key_param="key", differentiable=False)
def random_gen_neg_binomial(*, mu=1.0, alpha=1.0, shape=(1,), dtype=None,
                            ctx=None, key=None):
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    lam = jax.random.gamma(k1, r, tuple(shape)) * (mu * alpha)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(_dt(dtype))


@register_op("_random_randint", aliases=("random_randint", "randint"),
             key_param="key", differentiable=False)
def random_randint(*, low=0, high=None, shape=(1,), dtype=None, ctx=None,
                   key=None):
    return jax.random.randint(key, tuple(shape), low, high,
                              _dt(dtype or "int32"))


@register_op("_sample_multinomial", aliases=("sample_multinomial",),
             key_param="key", differentiable=False)
def sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32",
                       key=None):
    n = shape if isinstance(shape, int) else (shape[0] if shape else 1)
    logits = jnp.log(jnp.maximum(data, 1e-37))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
        if not shape:
            out = out[0]
    else:
        out = jax.random.categorical(key, logits[None, :, :],
                                     shape=(n, data.shape[0])).T
        if not shape:
            out = out[:, 0]
    return out.astype(_dt(dtype))


@register_op("_shuffle", aliases=("shuffle",), key_param="key",
             differentiable=False)
def shuffle(data, *, key=None):
    return jax.random.permutation(key, data, axis=0)


@register_op("sample_uniform", key_param="key", differentiable=False)
def sample_uniform(low, high, *, shape=(), dtype=None, key=None):
    s = tuple(low.shape) + (tuple(shape) if shape else ())
    u = jax.random.uniform(key, s, _dt(dtype))
    low_b = low.reshape(low.shape + (1,) * (len(s) - low.ndim))
    high_b = high.reshape(high.shape + (1,) * (len(s) - high.ndim))
    return low_b + u * (high_b - low_b)


@register_op("sample_normal", key_param="key", differentiable=False)
def sample_normal(mu, sigma, *, shape=(), dtype=None, key=None):
    s = tuple(mu.shape) + (tuple(shape) if shape else ())
    z = jax.random.normal(key, s, _dt(dtype))
    mu_b = mu.reshape(mu.shape + (1,) * (len(s) - mu.ndim))
    sig_b = sigma.reshape(sigma.shape + (1,) * (len(s) - sigma.ndim))
    return mu_b + z * sig_b


@register_op("_random_uniform_like", aliases=("uniform_like",),
             key_param="key", differentiable=False)
def uniform_like(data, *, low=0.0, high=1.0, key=None):
    return jax.random.uniform(key, data.shape, data.dtype, low, high)


@register_op("_random_normal_like", aliases=("normal_like",),
             key_param="key", differentiable=False)
def normal_like(data, *, loc=0.0, scale=1.0, key=None):
    return jax.random.normal(key, data.shape, data.dtype) * scale + loc
