"""Random sampling ops.

Reference parity: src/operator/random/ (sample_op.cc: uniform/normal/gamma/
exponential/poisson/negative_binomial/generalized_negative_binomial/randint,
multinomial, shuffle) — SURVEY.md §2.3 `random/`.  TPU-native: JAX threaded
PRNG; the dispatcher injects a fresh key per call (see ops/registry.py
``key_param``), replacing the reference's per-device generator arrays
(include/mxnet/random_generator.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..dtype import normalize_dtype
from .registry import register_op


def _dt(dtype):
    return normalize_dtype(dtype if dtype not in (None, "None") else "float32")


@register_op("_random_uniform", aliases=("random_uniform", "uniform"),
             key_param="key", differentiable=False)
def random_uniform(*, low=0.0, high=1.0, shape=(1,), dtype=None, ctx=None,
                   key=None):
    return jax.random.uniform(key, tuple(shape), _dt(dtype), low, high)


@register_op("_random_normal", aliases=("random_normal", "normal"),
             key_param="key", differentiable=False)
def random_normal(*, loc=0.0, scale=1.0, shape=(1,), dtype=None, ctx=None,
                  key=None):
    return jax.random.normal(key, tuple(shape), _dt(dtype)) * scale + loc


@register_op("_random_gamma", aliases=("random_gamma",), key_param="key",
             differentiable=False)
def random_gamma(*, alpha=1.0, beta=1.0, shape=(1,), dtype=None, ctx=None,
                 key=None):
    return jax.random.gamma(key, alpha, tuple(shape), _dt(dtype)) * beta


@register_op("_random_exponential", aliases=("random_exponential",),
             key_param="key", differentiable=False)
def random_exponential(*, lam=1.0, shape=(1,), dtype=None, ctx=None,
                       key=None):
    return jax.random.exponential(key, tuple(shape), _dt(dtype)) / lam


@register_op("_random_poisson", aliases=("random_poisson",), key_param="key",
             differentiable=False)
def random_poisson(*, lam=1.0, shape=(1,), dtype=None, ctx=None, key=None):
    return jax.random.poisson(key, lam, tuple(shape)).astype(_dt(dtype))


@register_op("_random_negative_binomial",
             aliases=("random_negative_binomial",), key_param="key",
             differentiable=False)
def random_negative_binomial(*, k=1, p=1.0, shape=(1,), dtype=None, ctx=None,
                             key=None):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, tuple(shape)).astype(_dt(dtype))


@register_op("_random_generalized_negative_binomial",
             aliases=("random_generalized_negative_binomial",),
             key_param="key", differentiable=False)
def random_gen_neg_binomial(*, mu=1.0, alpha=1.0, shape=(1,), dtype=None,
                            ctx=None, key=None):
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    lam = jax.random.gamma(k1, r, tuple(shape)) * (mu * alpha)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(_dt(dtype))


@register_op("_random_randint", aliases=("random_randint", "randint"),
             key_param="key", differentiable=False)
def random_randint(*, low=0, high=None, shape=(1,), dtype=None, ctx=None,
                   key=None):
    return jax.random.randint(key, tuple(shape), low, high,
                              _dt(dtype or "int32"))


@register_op("_sample_multinomial", aliases=("sample_multinomial",),
             key_param="key", differentiable=False)
def sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32",
                       key=None):
    n = shape if isinstance(shape, int) else (shape[0] if shape else 1)
    logits = jnp.log(jnp.maximum(data, 1e-37))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
        if not shape:
            out = out[0]
    else:
        out = jax.random.categorical(key, logits[None, :, :],
                                     shape=(n, data.shape[0])).T
        if not shape:
            out = out[:, 0]
    return out.astype(_dt(dtype))


@register_op("_shuffle", aliases=("shuffle",), key_param="key",
             differentiable=False)
def shuffle(data, *, key=None):
    return jax.random.permutation(key, data, axis=0)


@register_op("sample_uniform", key_param="key", differentiable=False)
def sample_uniform(low, high, *, shape=(), dtype=None, key=None):
    s = tuple(low.shape) + (tuple(shape) if shape else ())
    u = jax.random.uniform(key, s, _dt(dtype))
    low_b = low.reshape(low.shape + (1,) * (len(s) - low.ndim))
    high_b = high.reshape(high.shape + (1,) * (len(s) - high.ndim))
    return low_b + u * (high_b - low_b)


@register_op("sample_normal", key_param="key", differentiable=False)
def sample_normal(mu, sigma, *, shape=(), dtype=None, key=None):
    s = tuple(mu.shape) + (tuple(shape) if shape else ())
    z = jax.random.normal(key, s, _dt(dtype))
    mu_b = mu.reshape(mu.shape + (1,) * (len(s) - mu.ndim))
    sig_b = sigma.reshape(sigma.shape + (1,) * (len(s) - sigma.ndim))
    return mu_b + z * sig_b


def _bcast(p, s):
    """Broadcast a (tensor-valued) distribution parameter of shape
    ``p.shape`` against the output shape ``s = p.shape + extra``."""
    return p.reshape(tuple(p.shape) + (1,) * (len(s) - p.ndim))


@register_op("sample_gamma", key_param="key", differentiable=False)
def sample_gamma(alpha, beta, *, shape=(), dtype=None, key=None):
    """Per-element gamma: one draw per (alpha, beta) pair (reference
    src/operator/random/sample_op.cc SampleGamma)."""
    s = tuple(alpha.shape) + (tuple(shape) if shape else ())
    g = jax.random.gamma(key, _bcast(alpha, s), s, _dt(dtype))
    return g * _bcast(beta, s)


@register_op("sample_exponential", key_param="key", differentiable=False)
def sample_exponential(lam, *, shape=(), dtype=None, key=None):
    """Reference sample_op.cc SampleExponential (rate lambda)."""
    s = tuple(lam.shape) + (tuple(shape) if shape else ())
    e = jax.random.exponential(key, s, _dt(dtype))
    return e / _bcast(lam, s)


@register_op("sample_poisson", key_param="key", differentiable=False)
def sample_poisson(lam, *, shape=(), dtype=None, key=None):
    """Reference sample_op.cc SamplePoisson."""
    s = tuple(lam.shape) + (tuple(shape) if shape else ())
    return jax.random.poisson(key, _bcast(lam, s), s).astype(
        _dt(dtype))


@register_op("sample_negative_binomial", key_param="key",
             differentiable=False)
def sample_negative_binomial(k, p, *, shape=(), dtype=None, key=None):
    """Reference sample_op.cc SampleNegativeBinomial — gamma-Poisson
    mixture with per-element (k, p)."""
    s = tuple(k.shape) + (tuple(shape) if shape else ())
    k1, k2 = jax.random.split(key)
    kb, pb = _bcast(k, s), _bcast(p, s)
    lam = jax.random.gamma(k1, kb, s) * (1 - pb) / pb
    return jax.random.poisson(k2, lam, s).astype(_dt(dtype))


@register_op("sample_generalized_negative_binomial", key_param="key",
             differentiable=False)
def sample_gen_negative_binomial(mu, alpha, *, shape=(), dtype=None,
                                 key=None):
    """Reference sample_op.cc SampleGeneralizedNegativeBinomial."""
    s = tuple(mu.shape) + (tuple(shape) if shape else ())
    k1, k2 = jax.random.split(key)
    mub, ab = _bcast(mu, s), _bcast(alpha, s)
    lam = jax.random.gamma(k1, 1.0 / ab, s) * (mub * ab)
    return jax.random.poisson(k2, lam, s).astype(_dt(dtype))


@register_op("_random_uniform_like", aliases=("uniform_like",),
             key_param="key", differentiable=False)
def uniform_like(data, *, low=0.0, high=1.0, key=None):
    return jax.random.uniform(key, data.shape, data.dtype, low, high)


@register_op("_random_normal_like", aliases=("normal_like",),
             key_param="key", differentiable=False)
def normal_like(data, *, loc=0.0, scale=1.0, key=None):
    return jax.random.normal(key, data.shape, data.dtype) * scale + loc


# ------------------------------------------------------- pdf op family
# Reference: src/operator/random/pdf_op.cc — probability density (or
# log-density) of samples under parameterized distributions.
import jax.scipy.stats as _jstats  # noqa: E402
import jax.numpy as _jnp  # noqa: E402


def _pdf_out(logpdf, is_log):
    return logpdf if is_log else _jnp.exp(logpdf)


@register_op("_random_pdf_uniform", aliases=("random_pdf_uniform",))
def pdf_uniform(sample, low, high, *, is_log=False):
    inside = (sample >= low[..., None]) & (sample <= high[..., None])
    logp = _jnp.where(inside,
                      -_jnp.log(high[..., None] - low[..., None]),
                      -_jnp.inf)
    return _pdf_out(logp, is_log)


@register_op("_random_pdf_normal", aliases=("random_pdf_normal",))
def pdf_normal(sample, mu, sigma, *, is_log=False):
    logp = _jstats.norm.logpdf(sample, mu[..., None], sigma[..., None])
    return _pdf_out(logp, is_log)


@register_op("_random_pdf_gamma", aliases=("random_pdf_gamma",))
def pdf_gamma(sample, alpha, beta, *, is_log=False):
    # beta is the RATE: reference PDF_Gamma computes a*log(b) - b*x
    # (src/operator/random/pdf_op.h:121-136), even though its sampler
    # treats beta as scale — the upstream inconsistency is preserved
    logp = _jstats.gamma.logpdf(sample, alpha[..., None],
                                scale=1.0 / beta[..., None])
    return _pdf_out(logp, is_log)


@register_op("_random_pdf_exponential",
             aliases=("random_pdf_exponential",))
def pdf_exponential(sample, lam, *, is_log=False):
    logp = _jstats.expon.logpdf(sample, scale=1.0 / lam[..., None])
    return _pdf_out(logp, is_log)


@register_op("_random_pdf_poisson", aliases=("random_pdf_poisson",))
def pdf_poisson(sample, lam, *, is_log=False):
    logp = _jstats.poisson.logpmf(sample, lam[..., None])
    return _pdf_out(logp, is_log)


@register_op("_random_pdf_negative_binomial",
             aliases=("random_pdf_negative_binomial",))
def pdf_negative_binomial(sample, k, p, *, is_log=False):
    kk = k[..., None]
    pp = p[..., None]
    from jax.scipy.special import gammaln as _gammaln

    logp = (_gammaln(sample + kk) - _gammaln(sample + 1.0)
            - _gammaln(kk) + kk * _jnp.log(pp)
            + sample * _jnp.log1p(-pp))
    return _pdf_out(logp, is_log)


@register_op("_random_pdf_generalized_negative_binomial",
             aliases=("random_pdf_generalized_negative_binomial",))
def pdf_gen_negative_binomial(sample, mu, alpha, *, is_log=False):
    a = 1.0 / alpha[..., None]
    m = mu[..., None]
    p = a / (a + m)
    from jax.scipy.special import gammaln as _gammaln

    logp = (_gammaln(sample + a) - _gammaln(sample + 1.0) - _gammaln(a)
            + a * _jnp.log(p) + sample * _jnp.log1p(-p))
    return _pdf_out(logp, is_log)


@register_op("_random_pdf_dirichlet", aliases=("random_pdf_dirichlet",))
def pdf_dirichlet(sample, alpha, *, is_log=False):
    from jax.scipy.special import gammaln as _gammaln

    a = alpha
    logp = (_jnp.sum((a - 1.0) * _jnp.log(sample), axis=-1)
            + _gammaln(_jnp.sum(a, axis=-1))
            - _jnp.sum(_gammaln(a), axis=-1))
    return _pdf_out(logp, is_log)
