"""INT8 + FP8 quantization operators.

Reference parity: src/operator/quantization/ (6,057 LoC — quantize.cc,
quantize_v2.cc, dequantize.cc, requantize.cc, quantized_conv/fc/pooling/
flatten).  TPU-native: int8 matmul/conv accumulate in int32 on the MXU
via ``preferred_element_type`` — the same int8→int32 contract the
reference gets from cuDNN/MKLDNN int8 kernels.  Round 19 adds the fp8
family (``_contrib_quantize_fp8`` / ``_contrib_fp8_fully_connected`` /
``_contrib_fp8_conv``): e4m3 operands accumulating f32, real-domain
f32 output — no requantize stage, since fp8 needs only an amax.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

_INT8_RANGE = 127.0


def _minmax_scale(mn, mx):
    amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    return jnp.where(amax > 0, _INT8_RANGE / amax, 1.0), amax


@register_op("_contrib_quantize", num_outputs=3, differentiable=False)
def quantize(data, min_range, max_range, *, out_type="uint8"):
    """Reference: quantization/quantize.cc — float -> quantized with the
    given range.  uint8: affine [min,max] -> [0,255]; int8: symmetric."""
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(mx - mn, 1e-12)
        q = jnp.clip(jnp.round((data - mn) * scale), 0, 255).astype(
            jnp.uint8)
    else:
        scale, amax = _minmax_scale(mn, mx)
        q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, mn.reshape(1), mx.reshape(1)


@register_op("_contrib_quantize_v2", num_outputs=3, differentiable=False)
def quantize_v2(data, *, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """Reference: quantization/quantize_v2.cc — calibrated or on-the-fly
    range, symmetric int8."""
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    else:
        mn = data.min().astype(jnp.float32)
        mx = data.max().astype(jnp.float32)
    scale, amax = _minmax_scale(mn, mx)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, (-amax).reshape(1), amax.reshape(1)


@register_op("_contrib_dequantize", differentiable=False)
def dequantize(data, min_range, max_range, *, out_type="float32"):
    """Reference: quantization/dequantize.cc."""
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    if data.dtype == jnp.uint8:
        scale = jnp.maximum(mx - mn, 1e-12) / 255.0
        return data.astype(jnp.float32) * scale + mn
    amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    # int8 maps to ±127, int32 accumulators to ±(2^31-1) — the
    # reference's quantized range convention per dtype
    denom = _INT8_RANGE if data.dtype == jnp.int8 else \
        jnp.float32(2 ** 31 - 1)
    return data.astype(jnp.float32) * (amax / denom)


@register_op("_contrib_requantize", num_outputs=3, differentiable=False)
def requantize(data, min_range, max_range, *, out_type="int8",
               min_calib_range=None, max_calib_range=None):
    """Reference: quantization/requantize.cc — int32 accumulators back
    to int8 with a (possibly calibrated) output range."""
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    real = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        / jnp.float32(2 ** 31 - 1))
    if min_calib_range is not None and max_calib_range is not None:
        omax = jnp.float32(max(abs(min_calib_range),
                               abs(max_calib_range)))
    else:
        omax = jnp.maximum(jnp.abs(real).max(), 1e-12)
    q = jnp.clip(jnp.round(real * (_INT8_RANGE / omax)), -127,
                 127).astype(jnp.int8)
    return q, (-omax).reshape(1), omax.reshape(1)


@register_op("_contrib_quantized_fully_connected", num_outputs=3,
             differentiable=False)
def quantized_fully_connected(data, weight, bias, data_min, data_max,
                              weight_min, weight_max, bias_min, bias_max,
                              *, num_hidden, no_bias=False, flatten=True):
    """Reference: quantization/quantized_fully_connected.cc — int8 x
    int8 -> int32 accumulation (MXU native via preferred_element_type)."""
    d = data.reshape(data.shape[0], -1) if flatten else data
    acc = lax.dot_general(
        d.astype(jnp.int8), weight.astype(jnp.int8),
        (((d.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    d_amax = jnp.maximum(jnp.abs(data_min), jnp.abs(data_max)).reshape(())
    w_amax = jnp.maximum(jnp.abs(weight_min),
                         jnp.abs(weight_max)).reshape(())
    out_scale = (d_amax / _INT8_RANGE) * (w_amax / _INT8_RANGE)
    if not no_bias:
        b_amax = jnp.maximum(jnp.abs(bias_min),
                             jnp.abs(bias_max)).reshape(())
        b_real = bias.astype(jnp.float32) * (b_amax / _INT8_RANGE)
        acc = acc + jnp.round(b_real / jnp.maximum(out_scale, 1e-30)
                              ).astype(jnp.int32)
    omax = out_scale * jnp.float32(2 ** 31 - 1)
    return acc, (-omax).reshape(1), omax.reshape(1)


@register_op("_contrib_quantized_conv", num_outputs=3,
             differentiable=False)
def quantized_conv(data, weight, bias, data_min, data_max, weight_min,
                   weight_max, bias_min, bias_max, *, kernel, num_filter,
                   stride=None, pad=None, dilate=None, num_group=1,
                   no_bias=False, layout=None):
    """Reference: quantization/quantized_conv.cc — int8 conv with int32
    accumulation."""
    nd_ = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if nd_ == 2 else ("NCW", "OIW", "NCW"))
    acc = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    d_amax = jnp.maximum(jnp.abs(data_min), jnp.abs(data_max)).reshape(())
    w_amax = jnp.maximum(jnp.abs(weight_min),
                         jnp.abs(weight_max)).reshape(())
    out_scale = (d_amax / _INT8_RANGE) * (w_amax / _INT8_RANGE)
    if not no_bias:
        b_amax = jnp.maximum(jnp.abs(bias_min),
                             jnp.abs(bias_max)).reshape(())
        b_real = bias.astype(jnp.float32) * (b_amax / _INT8_RANGE)
        b_q = jnp.round(b_real / jnp.maximum(out_scale, 1e-30)).astype(
            jnp.int32)
        acc = acc + b_q.reshape((1, -1) + (1,) * nd_)
    omax = out_scale * jnp.float32(2 ** 31 - 1)
    return acc, (-omax).reshape(1), omax.reshape(1)


# ----- fp8 (round 19): e4m3 operands, f32 accumulation -----------------
# The fp8 inference arm mirrors the int8 shape — per-tensor symmetric
# scaling off a calibrated range — but needs only ONE statistic (amax)
# and NO requantize: the matmul/conv accumulates f32 on the MXU
# (preferred_element_type) and the output stays real-domain f32, so the
# q-triple stitching machinery never engages for fp8.
_FP8_MAX = 448.0  # e4m3fn finite max (the format has no inf)


@register_op("_contrib_quantize_fp8", num_outputs=2, differentiable=False)
def quantize_fp8(data, *, min_calib_range=None, max_calib_range=None):
    """float -> (e4m3, amax(1,)).  Mirrors quantize_v2's calibrated /
    on-the-fly range convention; symmetric amax scaling.  Values are
    clipped to ±448 BEFORE the cast — e4m3fn overflows to NaN, not inf,
    so an unclipped range excursion would poison the accumulator."""
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    else:
        mn = data.min().astype(jnp.float32)
        mx = data.max().astype(jnp.float32)
    amax = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-12)
    q = jnp.clip(data.astype(jnp.float32) * (_FP8_MAX / amax),
                 -_FP8_MAX, _FP8_MAX).astype(jnp.float8_e4m3fn)
    return q, amax.reshape(1)


@register_op("_contrib_fp8_fully_connected", differentiable=False)
def fp8_fully_connected(data, weight, bias, data_amax, weight_amax, *,
                        num_hidden, no_bias=False, flatten=True):
    """fp8 FC: e4m3 x e4m3 -> f32 accumulation (MXU native via
    preferred_element_type); the descale (d_amax/448)*(w_amax/448)
    recovers the real domain, bias is added there in f32.  Output is
    plain f32 — no quantized triple."""
    d = data.reshape(data.shape[0], -1) if flatten else data
    acc = lax.dot_general(
        d.astype(jnp.float8_e4m3fn), weight.astype(jnp.float8_e4m3fn),
        (((d.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    out = acc * ((data_amax.reshape(()) / _FP8_MAX)
                 * (weight_amax.reshape(()) / _FP8_MAX))
    if not no_bias:
        out = out + bias.astype(jnp.float32)
    return out


@register_op("_contrib_fp8_conv", differentiable=False)
def fp8_conv(data, weight, bias, data_amax, weight_amax, *, kernel,
             num_filter, stride=None, pad=None, dilate=None, num_group=1,
             no_bias=False, layout=None):
    """fp8 convolution: e4m3 operands, f32 accumulation, real-domain
    f32 output (same contract as :func:`fp8_fully_connected`)."""
    nd_ = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if nd_ == 2 else ("NCW", "OIW", "NCW"))
    acc = lax.conv_general_dilated(
        data.astype(jnp.float8_e4m3fn), weight.astype(jnp.float8_e4m3fn),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.float32)
    out = acc * ((data_amax.reshape(()) / _FP8_MAX)
                 * (weight_amax.reshape(()) / _FP8_MAX))
    if not no_bias:
        out = out + bias.astype(jnp.float32).reshape(
            (1, -1) + (1,) * nd_)
    return out


@register_op("_contrib_quantized_pooling", num_outputs=3,
             differentiable=False)
def quantized_pooling(data, data_min, data_max, *, kernel=(),
                      pool_type="max", global_pool=False, stride=None,
                      pad=None, pooling_convention="valid"):
    """Reference: quantization/quantized_pooling.cc — pooling preserves
    the quantization range."""
    from .conv import pooling as _pooling

    if pool_type == "avg":
        # the average accumulates in float; the cast back to the int8
        # code domain must round to NEAREST (round-18 fix: astype alone
        # truncates toward zero, biasing every averaged window toward 0
        # vs the dequantized-fp32 reference)
        out = _pooling(data.astype(jnp.float32), kernel=kernel,
                       pool_type=pool_type, global_pool=global_pool,
                       stride=stride, pad=pad,
                       pooling_convention=pooling_convention)
        out = jnp.rint(out)
    else:
        out = _pooling(data.astype(jnp.int32), kernel=kernel,
                       pool_type=pool_type, global_pool=global_pool,
                       stride=stride, pad=pad,
                       pooling_convention=pooling_convention)
    return out.astype(data.dtype), data_min, data_max


@register_op("_contrib_quantized_flatten", num_outputs=3,
             differentiable=False)
def quantized_flatten(data, data_min, data_max):
    return data.reshape(data.shape[0], -1), data_min, data_max
