"""NumPy-semantics operators backing ``mx.np`` (the ``_npi_*`` family).

Reference parity: src/operator/numpy/ (15,457 LoC — einsum with path
optimization np_einsum_op.cc, tensordot np_tensordot_op.cc, unique
np_unique_op.cc, nonzero np_nonzero_op.cc, window ops np_window_op.cc,
tri ops np_tri_op.cc, cumprod/diff/trace/...).  TPU-native: jnp already
implements numpy semantics, so most ops are direct registrations; the
dynamic-shape ops (unique, nonzero) follow the fixed-size+mask idiom
from SURVEY.md §7 — XLA-compatible padded outputs plus a valid count.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from .registry import register_op

# ------------------------------------------------------------- contraction


@register_op("_npi_einsum")
def einsum(*operands, subscripts, optimize=True):
    """Reference: src/operator/numpy/np_einsum_op.cc (with path
    optimizer).  XLA's dot-general fusion takes the role of the
    hand-rolled contraction-path search; ``optimize`` picks the
    opt_einsum path strategy."""
    return jnp.einsum(subscripts, *operands,
                      optimize="optimal" if optimize else False)


@register_op("_npi_tensordot")
def tensordot(a, b, *, a_axes_summed=None, b_axes_summed=None, axes=2):
    """Reference: src/operator/numpy/np_tensordot_op.cc."""
    if a_axes_summed is not None:
        axes = (tuple(a_axes_summed), tuple(b_axes_summed))
    return jnp.tensordot(a, b, axes=axes)


@register_op("_npi_dot")
def np_dot(a, b):
    return jnp.dot(a, b)


@register_op("_npi_vdot")
def vdot(a, b):
    return jnp.vdot(a, b)


@register_op("_npi_inner")
def inner(a, b):
    return jnp.inner(a, b)


@register_op("_npi_outer")
def outer(a, b):
    return jnp.outer(a, b)


@register_op("_npi_kron")
def kron(a, b):
    return jnp.kron(a, b)


# ----------------------------------------------- dynamic-shape (masked)
@register_op("_npi_unique", num_outputs=lambda p: 1
             + bool(p.get("return_index")) + bool(p.get("return_inverse"))
             + bool(p.get("return_counts")), differentiable=False)
def unique(data, *, return_index=False, return_inverse=False,
           return_counts=False, axis=None, size=None, fill_value=0):
    """Reference: src/operator/numpy/np_unique_op.cc.

    XLA contract: with ``size`` given (or traced input), outputs are
    padded/truncated to ``size`` (jnp.unique fixed-size mode); eagerly
    without ``size``, exact dynamic shapes come back (host path, like
    the reference's CPU-only kernel).
    """
    kw = dict(return_index=return_index, return_inverse=return_inverse,
              return_counts=return_counts, axis=axis)
    if size is not None:
        kw.update(size=size, fill_value=fill_value)
    out = jnp.unique(data, **kw)
    if not (return_index or return_inverse or return_counts):
        return out
    return tuple(out)


@register_op("_npi_nonzero", differentiable=False)
def nonzero(data, *, size=None, fill_value=-1):
    """Reference: src/operator/numpy/np_nonzero_op.cc — returns an
    (nnz, ndim) int64 index matrix (the reference's transposed layout).
    Fixed-size+mask under trace (rows of ``fill_value`` pad the tail)."""
    idx = jnp.nonzero(data, size=size, fill_value=fill_value)
    # reference emits int64; on 32-bit jax default this stays int32
    return jnp.stack(idx, axis=-1).astype("int64" if jax.config.x64_enabled
                                          else "int32")


# ------------------------------------------------------------ cumulative
@register_op("_npi_cumprod")
def cumprod(data, *, axis=None, dtype=None):
    return jnp.cumprod(data, axis=axis, dtype=dtype)


@register_op("_npi_diff")
def diff(data, *, n=1, axis=-1):
    """Reference: src/operator/numpy/np_diff_op.cc."""
    return jnp.diff(data, n=n, axis=axis)


@register_op("_npi_ediff1d")
def ediff1d(data, *, to_end=None, to_begin=None):
    return jnp.ediff1d(data, to_end=to_end, to_begin=to_begin)


@register_op("_npi_trace")
def trace(data, *, offset=0, axis1=0, axis2=1):
    """Reference: src/operator/numpy/np_trace_op.cc."""
    return jnp.trace(data, offset=offset, axis1=axis1, axis2=axis2)


# --------------------------------------------------------------- tri ops
@register_op("_npi_tri", differentiable=False)
def tri(*, N, M=None, k=0, dtype="float32"):
    """Reference: src/operator/numpy/np_tri_op.cc."""
    return jnp.tri(N, M, k, dtype=dtype)


@register_op("_npi_tril")
def tril(data, *, k=0):
    return jnp.tril(data, k=k)


@register_op("_npi_triu")
def triu(data, *, k=0):
    return jnp.triu(data, k=k)


# ------------------------------------------------------------ window ops
@register_op("_npi_hanning", differentiable=False)
def hanning(*, M, dtype="float32"):
    """Reference: src/operator/numpy/np_window_op.cc."""
    return jnp.hanning(M).astype(dtype)


@register_op("_npi_hamming", differentiable=False)
def hamming(*, M, dtype="float32"):
    return jnp.hamming(M).astype(dtype)


@register_op("_npi_blackman", differentiable=False)
def blackman(*, M, dtype="float32"):
    return jnp.blackman(M).astype(dtype)


# ------------------------------------------------------- rearrangement
@register_op("_npi_roll")
def roll(data, *, shift=None, axis=None):
    return jnp.roll(data, shift, axis=axis)


@register_op("_npi_rot90")
def rot90(data, *, k=1, axes=(0, 1)):
    return jnp.rot90(data, k=k, axes=tuple(axes))


@register_op("_npi_flipud")
def flipud(data):
    return jnp.flipud(data)


@register_op("_npi_fliplr")
def fliplr(data):
    return jnp.fliplr(data)


@register_op("_npi_moveaxis")
def moveaxis(data, *, source, destination):
    return jnp.moveaxis(data, source, destination)


@register_op("_npi_rollaxis")
def rollaxis(data, *, axis, start=0):
    return jnp.rollaxis(data, axis, start)


@register_op("_npi_column_stack")
def column_stack(*arrays, num_args=1):
    return jnp.column_stack(arrays)


@register_op("_npi_hstack")
def hstack(*arrays, num_args=1):
    return jnp.hstack(arrays)


@register_op("_npi_vstack")
def vstack(*arrays, num_args=1):
    return jnp.vstack(arrays)


@register_op("_npi_dstack")
def dstack(*arrays, num_args=1):
    return jnp.dstack(arrays)


@register_op("_npi_atleast_1d", num_outputs=lambda p: p.get("num_args", 1))
def atleast_1d(*arrays, num_args=1):
    out = jnp.atleast_1d(*arrays)
    return out


@register_op("_npi_squeeze")
def np_squeeze(data, *, axis=None):
    return jnp.squeeze(data, axis=axis)


# ----------------------------------------------------------- statistics
@register_op("_npi_std")
def std(data, *, axis=None, ddof=0, keepdims=False):
    return jnp.std(data, axis=axis, ddof=ddof, keepdims=keepdims)


@register_op("_npi_var")
def var(data, *, axis=None, ddof=0, keepdims=False):
    return jnp.var(data, axis=axis, ddof=ddof, keepdims=keepdims)


@register_op("_npi_average")
def average(a, weights=None, *, axis=None, returned=False):
    if returned:
        out, wsum = jnp.average(a, axis=axis, weights=weights,
                                returned=True)
        return out, wsum
    return jnp.average(a, axis=axis, weights=weights)


@register_op("_npi_median", differentiable=False)
def median(data, *, axis=None, keepdims=False):
    return jnp.median(data, axis=axis, keepdims=keepdims)


@register_op("_npi_percentile", differentiable=False)
def percentile(data, *, q, axis=None, interpolation="linear",
               keepdims=False):
    return jnp.percentile(data, jnp.asarray(q), axis=axis,
                          method=interpolation, keepdims=keepdims)


@register_op("_npi_quantile", differentiable=False)
def quantile(data, *, q, axis=None, interpolation="linear",
             keepdims=False):
    return jnp.quantile(data, jnp.asarray(q), axis=axis,
                        method=interpolation, keepdims=keepdims)


@register_op("_npi_histogram", differentiable=False, num_outputs=2)
def histogram(data, *, bins=10, range=None):
    """Reference: src/operator/tensor/histogram.cc."""
    hist, edges = jnp.histogram(data, bins=bins, range=range)
    return hist, edges


@register_op("_npi_bincount", differentiable=False)
def bincount(data, weights=None, *, minlength=0, length=None):
    return jnp.bincount(data.astype(jnp.int32), weights=weights,
                        minlength=minlength, length=length)


@register_op("_npi_corrcoef", differentiable=False)
def corrcoef(x):
    return jnp.corrcoef(x)


# ------------------------------------------------------------- logic ops
@register_op("_npi_isnan", differentiable=False)
def isnan(data):
    return jnp.isnan(data)


@register_op("_npi_isinf", differentiable=False)
def isinf(data):
    return jnp.isinf(data)


@register_op("_npi_isfinite", differentiable=False)
def isfinite(data):
    return jnp.isfinite(data)


@register_op("_npi_isposinf", differentiable=False)
def isposinf(data):
    return jnp.isposinf(data)


@register_op("_npi_isneginf", differentiable=False)
def isneginf(data):
    return jnp.isneginf(data)


@register_op("_npi_logical_and", differentiable=False)
def logical_and(a, b):
    return jnp.logical_and(a, b)


@register_op("_npi_logical_or", differentiable=False)
def logical_or(a, b):
    return jnp.logical_or(a, b)


@register_op("_npi_logical_xor", differentiable=False)
def logical_xor(a, b):
    return jnp.logical_xor(a, b)


@register_op("_npi_array_equal", differentiable=False)
def array_equal(a, b):
    return jnp.array_equal(a, b)


# ------------------------------------------------------------- misc math
@register_op("_npi_interp", differentiable=False)
def interp(x, xp, fp, *, left=None, right=None):
    return jnp.interp(x, xp, fp, left=left, right=right)


@register_op("_npi_cross")
def cross(a, b, *, axisa=-1, axisb=-1, axisc=-1, axis=None):
    return jnp.cross(a, b, axisa=axisa, axisb=axisb, axisc=axisc,
                     axis=axis)


@register_op("_npi_heaviside")
def heaviside(x1, x2):
    return jnp.heaviside(x1, x2)


@register_op("_npi_copysign")
def copysign(x1, x2):
    return jnp.copysign(x1, x2)


@register_op("_npi_frexp", num_outputs=2, differentiable=False)
def frexp(x):
    m, e = jnp.frexp(x)
    return m, e


@register_op("_npi_ldexp")
def ldexp(x1, x2):
    return jnp.ldexp(x1, x2.astype(jnp.int32))


@register_op("_npi_nan_to_num")
def nan_to_num(data, *, copy=True, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(data, nan=nan, posinf=posinf, neginf=neginf)


@register_op("_npi_deg2rad")
def deg2rad(data):
    return jnp.deg2rad(data)


@register_op("_npi_rad2deg")
def rad2deg(data):
    return jnp.rad2deg(data)


@register_op("_npi_polyval")
def polyval(p, x):
    return jnp.polyval(p, x)


@register_op("_npi_lcm", differentiable=False)
def lcm(a, b):
    return jnp.lcm(a.astype(jnp.int32), b.astype(jnp.int32))


@register_op("_npi_gcd", differentiable=False)
def gcd(a, b):
    return jnp.gcd(a.astype(jnp.int32), b.astype(jnp.int32))


@register_op("_npi_fmod")
def fmod(a, b):
    return jnp.fmod(a, b)


@register_op("_npi_floor_divide")
def floor_divide(a, b):
    return jnp.floor_divide(a, b)


@register_op("_npi_true_divide")
def true_divide(a, b):
    return jnp.true_divide(a, b)


@register_op("_npi_searchsorted", differentiable=False)
def searchsorted(a, v, *, side="left"):
    return jnp.searchsorted(a, v, side=side)


@register_op("_npi_digitize", differentiable=False)
def digitize(x, bins, *, right=False):
    return jnp.digitize(x, bins, right=right)


@register_op("_npi_meshgrid", num_outputs=lambda p: p.get("num_args", 1),
             differentiable=False)
def meshgrid(*arrays, num_args=1, indexing="xy"):
    return tuple(jnp.meshgrid(*arrays, indexing=indexing))


@register_op("_npi_indices", differentiable=False)
def indices(*, dimensions, dtype="int32"):
    return jnp.indices(tuple(dimensions)).astype(dtype)


@register_op("_npi_may_share_memory", differentiable=False)
def may_share_memory(a, b):
    return jnp.zeros((1,), dtype=bool)  # functional arrays never share


@register_op("_npi_insert", differentiable=False)
def np_insert(arr, values, *, obj, axis=None):
    return jnp.insert(arr, obj, values, axis=axis)


@register_op("_npi_delete", differentiable=False)
def np_delete(arr, *, obj, axis=None):
    return jnp.delete(arr, obj, axis=axis)


@register_op("_npi_resize", differentiable=False)
def np_resize(arr, *, new_shape):
    return jnp.resize(arr, tuple(new_shape))


@register_op("_npi_full_like", differentiable=False)
def full_like(a, *, fill_value, dtype=None):
    return jnp.full_like(a, fill_value, dtype=dtype)


# --------------------------------------------------------------- linalg
# Reference: src/operator/numpy/linalg/ — consumed by mx.np.linalg.
def _reg(name, fn, nout=1, diff=True):
    register_op(name, num_outputs=nout, differentiable=diff)(fn)


_reg("_npi_norm", lambda x, *, ord=None, axis=None, keepdims=False:
     jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdims))
_reg("_npi_svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=False)),
     nout=3)
_reg("_npi_cholesky", lambda a: jnp.linalg.cholesky(a))
_reg("_npi_qr", lambda a: tuple(jnp.linalg.qr(a)), nout=2)
_reg("_npi_inv", lambda a: jnp.linalg.inv(a))
_reg("_npi_pinv", lambda a, *, rcond=1e-15: jnp.linalg.pinv(a,
                                                            rcond=rcond))
_reg("_npi_det", lambda a: jnp.linalg.det(a))
_reg("_npi_slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), nout=2)
_reg("_npi_solve", lambda a, b: jnp.linalg.solve(a, b))
_reg("_npi_eigh", lambda a: tuple(jnp.linalg.eigh(a)), nout=2)
_reg("_npi_eigvalsh", lambda a: jnp.linalg.eigvalsh(a))
_reg("_npi_matrix_rank",
     lambda a, *, tol=None: jnp.linalg.matrix_rank(a, tol=tol),
     diff=False)
_reg("_npi_matrix_power", lambda a, *, n: jnp.linalg.matrix_power(a, n))
_reg("_npi_lstsq", lambda a, b, *, rcond=None:
     tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), nout=4, diff=False)
_reg("_npi_tensorinv", lambda a, *, ind=2: jnp.linalg.tensorinv(a,
                                                                ind=ind))
_reg("_npi_tensorsolve", lambda a, b, *, axes=None:
     jnp.linalg.tensorsolve(a, b, axes=axes))

# round 3: concat/gather/diag/window/bitwise families
# (reference: src/operator/numpy/np_matrix_op.cc, np_window_op.cc,
#  np_elemwise_broadcast_logic_op.cc)
_reg("_npi_concatenate",
     lambda *arrs, axis=0: jnp.concatenate(arrs, axis=axis))
_reg("_npi_take_along_axis",
     lambda arr, idx, *, axis: jnp.take_along_axis(
         arr, idx.astype(jnp.int32), axis=axis))
_reg("_npi_bartlett",
     lambda *, M, dtype="float32": jnp.asarray(onp.bartlett(int(M)),
                                               dtype=dtype), diff=False)
_reg("_npi_diagonal",
     lambda a, *, offset=0, axis1=0, axis2=1: jnp.diagonal(
         a, offset=offset, axis1=axis1, axis2=axis2))
_reg("_npi_diagflat", lambda v, *, k=0: jnp.diagflat(v, k=k))


def _as_int(x):
    # numpy raises for bitwise ops on floats — silently truncating to
    # int32 would be a semantic divergence from the contract these ops
    # mirror; integer dtypes pass through untouched (int64 shifts must
    # not narrow)
    if jnp.issubdtype(x.dtype, jnp.floating):
        raise TypeError(
            "bitwise operations are not supported for floating dtypes "
            f"(got {x.dtype}); cast to an integer dtype first")
    return x


_reg("_npi_bitwise_and",
     lambda a, b: jnp.bitwise_and(_as_int(a), _as_int(b)), diff=False)
_reg("_npi_bitwise_or",
     lambda a, b: jnp.bitwise_or(_as_int(a), _as_int(b)), diff=False)
_reg("_npi_bitwise_xor",
     lambda a, b: jnp.bitwise_xor(_as_int(a), _as_int(b)), diff=False)
_reg("_npi_bitwise_not",
     lambda a: jnp.bitwise_not(_as_int(a)), diff=False)
_reg("_npi_left_shift",
     lambda a, b: jnp.left_shift(_as_int(a), _as_int(b)), diff=False)
_reg("_npi_right_shift",
     lambda a, b: jnp.right_shift(_as_int(a), _as_int(b)), diff=False)
