"""Neural-network ops: FullyConnected, activations, softmax family,
normalization layers, Dropout, loss/output ops.

Reference parity: src/operator/nn/ (fully_connected.cc:258-348, activation,
softmax, batch_norm, layer_norm, group_norm, dropout, lrn, l2_normalization)
and the *Output ops (src/operator/softmax_output.cc, regression_output).
TPU-native notes: FullyConnected/conv are MXU work — we keep them as plain
lax/jnp calls so XLA fuses the elementwise epilogues (bias, activation)
into the matmul; the reference needed cuDNN + a pointwise-fusion JIT pass
for the same effect.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("FullyConnected", aliases=("_FullyConnected",))
def fully_connected(data, weight, bias=None, *, num_hidden, no_bias=False,
                    flatten=True):
    """Reference: src/operator/nn/fully_connected.cc:258."""
    if flatten:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    out = jnp.dot(x, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    return out


@register_op("Activation")
def activation(x, *, act_type):
    if act_type == "relu":
        return jax.nn.relu(x)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return jax.nn.softplus(x)
    if act_type == "softsign":
        return jax.nn.soft_sign(x)
    raise ValueError(f"unknown act_type {act_type}")


@register_op("LeakyReLU")
def leaky_relu(*inputs, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    """Reference: src/operator/leaky_relu.cc (leaky/prelu/elu/selu/gelu)."""
    x = inputs[0]
    if act_type == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act_type == "prelu":
        gamma = inputs[1]
        if gamma.ndim < x.ndim and gamma.size > 1:
            shape = [1] * x.ndim
            shape[1] = gamma.size
            gamma = gamma.reshape(shape)
        return jnp.where(x > 0, x, gamma * x)
    if act_type == "elu":
        return jnp.where(x > 0, x, slope * jnp.expm1(x))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "rrelu":
        s = (lower_bound + upper_bound) / 2.0
        return jnp.where(x > 0, x, s * x)
    raise ValueError(f"unknown act_type {act_type}")


@register_op("softmax")
def softmax(x, length=None, *, axis=-1, temperature=None, use_length=False,
            dtype=None):
    if temperature:
        x = x / temperature
    if use_length and length is not None:
        pos = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = -1
        mask = pos.reshape(shape) < jnp.expand_dims(length, axis)
        x = jnp.where(mask, x, -jnp.inf)
        r = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, r, 0.0)
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(x, *, axis=-1, temperature=None, dtype=None,
                use_length=False):
    if temperature:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register_op("softmin")
def softmin(x, *, axis=-1, temperature=None, dtype=None, use_length=False):
    return jax.nn.softmax(-x, axis=axis)


@register_op("SoftmaxActivation")
def softmax_activation(x, *, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


@register_op("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    lp = jax.nn.log_softmax(data, axis=-1)
    nll = -jnp.take_along_axis(lp, label.astype(jnp.int32)[:, None], axis=-1)
    return jnp.sum(nll)


# ----------------------------------------------------------- BatchNorm
def _mean_var_nout(p):
    return 3 if p.get("output_mean_var") else 1


def _bn_stats(data, axis):
    """fp32 batch stats.

    For half-precision data (bf16/fp16): one pass — E[x] and E[x^2] are
    sibling reduces over the same input, so XLA multi-output-fuses them
    into a SINGLE read of the activation (the two-pass subtract-mean
    form reads it twice and serializes — measured +1.1 ms/step on
    ResNet-50 bs128).  Cancellation in E[x^2]-E[x]^2 is bounded by fp32
    accumulation: worst case ~|mean|^2 * 2^-24 * sqrt(N), negligible
    next to the half-precision quantization of the data itself; var is
    clamped at 0.

    For fp32/fp64 data the one-pass form can cancel catastrophically
    (|mean| >> std leaves no significant digits in E[x^2]-E[x]^2), so
    the numerically-safe two-pass form is kept — those runs are not on
    the bf16 fast path anyway."""
    red = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=red)
    if data.dtype in (jnp.bfloat16, jnp.float16):
        ex2 = jnp.mean(jnp.square(x32), axis=red)
        var = jnp.maximum(ex2 - jnp.square(mean), 0.0)
    else:
        bshape = [1] * data.ndim
        bshape[axis % data.ndim] = data.shape[axis % data.ndim]
        var = jnp.mean(jnp.square(x32 - mean.reshape(bshape)), axis=red)
    return mean, var


def _bn_train_fwd(data, gamma, beta, eps, axis, fix_gamma):
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = [1] * data.ndim
    bshape[ax] = data.shape[ax]
    mean, var = _bn_stats(data, axis)
    inv = jax.lax.rsqrt(var + eps)
    g32 = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
    scale = (inv * g32).reshape(bshape)
    shift = (beta.astype(jnp.float32)
             - mean * inv * g32).reshape(bshape)
    out = (data.astype(jnp.float32) * scale + shift).astype(data.dtype)
    res = (data, gamma, mean, inv, red, bshape)
    return (out, mean, var), res


def _bn_train_bwd(eps, axis, fix_gamma, res, cts):
    """Fused BN backward (the cuDNN BatchNormalizationBackward analog,
    reference batch_norm.cu): residuals are the ORIGINAL bf16 x plus
    per-channel stats — no fp32 activation-sized tensors survive the
    forward, which halves the train-step HBM traffic.

    An output-recompute variant (InPlace-ABN: xhat = (y-beta)/gamma from
    the materialized BN output) was tried in r05 and REVERTED: step time
    measured neutral on v5e (XLA's fusion graph had already deduplicated
    the y read), while gamma==0 — the standard zero-init-gamma residual
    recipe — makes xhat unrecoverable and silently freezes dgamma at 0,
    and small-|gamma| bf16 recovery cancels catastrophically."""
    data, gamma, mean, inv, red, bshape = res
    dy, dmean_ct, dvar_ct = cts
    n = 1
    for i in red:
        n *= data.shape[i]
    x32 = data.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    g32 = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
    xhat = (x32 - mean.reshape(bshape)) * inv.reshape(bshape)
    sum_dy = jnp.sum(dy32, axis=red)
    sum_dy_xhat = jnp.sum(dy32 * xhat, axis=red)
    # d/dx of the normalized output (batch stats participate)
    dx32 = (inv * g32).reshape(bshape) * (
        dy32 - (sum_dy / n).reshape(bshape)
        - xhat * (sum_dy_xhat / n).reshape(bshape))
    # cotangents flowing into the mean/var outputs (moving-average
    # update runs under autograd.pause -> normally zero, kept for
    # correctness of output_mean_var users)
    if dmean_ct is not None:
        dx32 = dx32 + (dmean_ct / n).reshape(bshape)
    if dvar_ct is not None:
        dx32 = dx32 + (dvar_ct * 2.0 / n).reshape(bshape) \
            * (x32 - mean.reshape(bshape))
    dgamma = jnp.zeros_like(gamma) if fix_gamma \
        else sum_dy_xhat.astype(gamma.dtype)
    dbeta = sum_dy.astype(gamma.dtype)
    return dx32.astype(data.dtype), dgamma, dbeta


def _bn_train_primal(data, gamma, beta, eps, axis, fix_gamma):
    return _bn_train_fwd(data, gamma, beta, eps, axis, fix_gamma)[0]


_bn_train = jax.custom_vjp(_bn_train_primal, nondiff_argnums=(3, 4, 5))
_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@register_op("BatchNorm", aliases=("BatchNorm_v1",),
             num_outputs=_mean_var_nout, train_param="train")
def batch_norm(data, gamma, beta, moving_mean, moving_var, *, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, train=False):
    """Reference: src/operator/nn/batch_norm.cc.

    Pure function: with output_mean_var returns (out, batch_mean,
    batch_var).  The caller (gluon BatchNorm layer / executor) folds batch
    stats into the moving aux arrays — the reference op mutates its aux
    inputs in-place instead, which has no XLA analog.
    """
    if train and not use_global_stats:
        # fused train path: custom VJP keeps residuals to the original
        # activation + per-channel stats (see _bn_train_bwd)
        out, mean, var = _bn_train(data, gamma, beta, float(eps), int(axis),
                                   bool(fix_gamma))
        if output_mean_var:
            return out, mean, var
        return out

    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]
    # stats in fp32 regardless of activation dtype (bf16 AMP-safe);
    # output cast back so downstream matmuls stay on the bf16 MXU path
    mean, var = (moving_mean.astype(jnp.float32),
                 moving_var.astype(jnp.float32))
    inv = jax.lax.rsqrt(var + eps)
    g32 = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
    scale = (inv * g32).reshape(bshape)
    shift = (beta.astype(jnp.float32) - mean * inv * g32).reshape(bshape)
    out = (data.astype(jnp.float32) * scale + shift).astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register_op("LayerNorm", num_outputs=_mean_var_nout)
def layer_norm(data, gamma, beta, *, axis=-1, eps=1e-5,
               output_mean_var=False):
    """Reference: src/operator/nn/layer_norm.cc."""
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    ax = axis % data.ndim
    shape[ax] = data.shape[ax]
    out = (data - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)
    return out


@register_op("InstanceNorm")
def instance_norm(data, gamma, beta, *, eps=1e-3):
    """Reference: src/operator/instance_norm.cc (normalize over spatial)."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    shape = [1, data.shape[1]] + [1] * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(shape) \
        + beta.reshape(shape)


@register_op("GroupNorm", num_outputs=_mean_var_nout)
def group_norm(data, gamma, beta, *, num_groups=1, eps=1e-5,
               output_mean_var=False):
    """Reference: src/operator/nn/group_norm.cc."""
    n, c = data.shape[0], data.shape[1]
    rest = data.shape[2:]
    x = data.reshape(n, num_groups, c // num_groups, *rest)
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    xn = ((x - mean) * jax.lax.rsqrt(var + eps)).reshape(data.shape)
    if gamma.size == num_groups != c:
        # reference layer keeps per-group affine params
        # (python/mxnet/gluon/nn/basic_layers.py GroupNorm)
        gamma = jnp.repeat(gamma, c // num_groups)
        beta = jnp.repeat(beta, c // num_groups)
    shape = [1, c] + [1] * (data.ndim - 2)
    out = xn * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean), jnp.squeeze(var)
    return out


@register_op("L2Normalization")
def l2_normalization(data, *, eps=1e-10, mode="instance"):
    """Reference: src/operator/l2_normalization.cc."""
    if mode == "instance":
        red = tuple(range(1, data.ndim))
    elif mode == "channel":
        red = (1,)
    else:  # spatial
        red = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    return data / norm


@register_op("LRN")
def lrn(data, *, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Reference: src/operator/nn/lrn.cc (cross-channel normalization)."""
    sq = jnp.square(data)
    half = nsize // 2
    pad = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2))
    acc = jnp.zeros_like(data)
    for i in range(nsize):
        acc = acc + jax.lax.dynamic_slice_in_dim(pad, i, data.shape[1], 1)
    norm = jnp.power(knorm + alpha / nsize * acc, beta)
    return data / norm


@register_op("Dropout", key_param="key", train_param="train")
def dropout(data, *, p=0.5, mode="training", axes=(), cudnn_off=False,
            key=None, train=False):
    """Reference: src/operator/nn/dropout.cc (scaled Bernoulli mask)."""
    if (not train and mode != "always") or p == 0:
        return data
    shape = list(data.shape)
    if axes:
        for a in axes:
            shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype) \
        / keep
    return data * mask


# -------------------------------------------------- output ops (custom vjp)
# These ops have *loss-style* backward semantics decoupled from their
# forward values (softmax_output.cc: grad = softmax - one_hot(label)).
@jax.custom_vjp
def _softmax_output(data, label, grad_scale, ignore_label, use_ignore,
                    smooth_alpha, normalize):
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        smooth_alpha, normalize):
    out = jax.nn.softmax(data, axis=-1)
    return out, (out, label, grad_scale, ignore_label, use_ignore,
                 smooth_alpha, normalize)


def _softmax_output_bwd(res, g):
    out, label, grad_scale, ignore_label, use_ignore, smooth_alpha, \
        normalize = res
    k = out.shape[-1]
    oh = jax.nn.one_hot(label.astype(jnp.int32), k, dtype=out.dtype)
    if smooth_alpha:
        oh = oh * (1 - smooth_alpha) + smooth_alpha / (k - 1) * (1 - oh)
    grad = out - oh
    if use_ignore:
        keep = (label != ignore_label).astype(out.dtype)
        grad = grad * keep[..., None]
    scale = grad_scale
    if normalize:
        scale = scale / out.shape[0]
    return (grad * scale, None, None, None, None, None, None)


_softmax_output.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register_op("SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, *, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", smooth_alpha=0.0,
                   out_grad=False):
    if multi_output or data.ndim > 2:
        # class axis 1: move to last for the shared impl
        perm = (0,) + tuple(range(2, data.ndim)) + (1,)
        inv = tuple(onp_argsort(perm))
        out = _softmax_output(jnp.transpose(data, perm), label, grad_scale,
                              ignore_label, use_ignore, smooth_alpha,
                              normalization == "valid")
        return jnp.transpose(out, inv)
    return _softmax_output(data, label, grad_scale, ignore_label, use_ignore,
                           smooth_alpha, normalization == "valid")


def onp_argsort(perm):
    import numpy as onp

    return onp.argsort(perm)


def _make_regression(transform, grad_fn, name):
    """Regression output ops: forward transform, loss-style backward
    ``grad_fn(pred, label) * grad_scale / batch`` (reference
    src/operator/regression_output-inl.h)."""

    @jax.custom_vjp
    def _op(data, label, grad_scale):
        return transform(data)

    def _fwd(data, label, grad_scale):
        out = transform(data)
        return out, (out, label, grad_scale)

    def _bwd(res, g):
        out, label, grad_scale = res
        batch = out.shape[0] if out.ndim else 1
        return (grad_fn(out, label) * (grad_scale / batch), None, None)

    _op.defvjp(_fwd, _bwd)

    @register_op(name)
    def _reg(data, label, *, grad_scale=1.0):
        return _op(data, label.reshape(data.shape), grad_scale)

    return _reg


_make_regression(lambda x: x, lambda o, l: o - l, "LinearRegressionOutput")
_make_regression(jax.nn.sigmoid, lambda o, l: o - l,
                 "LogisticRegressionOutput")
_make_regression(lambda x: x, lambda o, l: jnp.sign(o - l),
                 "MAERegressionOutput")


@register_op("CTCLoss", aliases=("ctc_loss", "_contrib_CTCLoss",
                                "_contrib_ctc_loss"))
def ctc_loss(data, label, data_lengths=None, label_lengths=None, *,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """Reference: src/operator/nn/ctc_loss.cc.  data: (T, N, C)."""
    import optax

    t, n, c = data.shape
    logits = jnp.transpose(data, (1, 0, 2))  # (N, T, C)
    if use_data_lengths and data_lengths is not None:
        lp = jnp.arange(t)[None, :] >= data_lengths[:, None]
    else:
        lp = jnp.zeros((n, t), dtype=jnp.float32)
    labels = label.astype(jnp.int32)
    if use_label_lengths and label_lengths is not None:
        pad = jnp.arange(labels.shape[1])[None, :] >= label_lengths[:, None]
    else:
        # reference padding convention (src/operator/nn/ctc_loss-inl.h:79):
        # 'first' pads with 0 (labels are 1-based, blank=0); 'last' pads
        # with -1 (labels 0-based, blank=c-1)
        pad = labels == 0 if blank_label == "first" else labels < 0
    if blank_label == "first":
        # optax uses blank=0 as well; labels already 1-based w.r.t. blank
        pass
    else:
        # blank is last (= c-1): rotate logits so blank becomes 0 and
        # shift labels to 1-based
        logits = jnp.concatenate([logits[..., -1:], logits[..., :-1]], -1)
        labels = jnp.where(labels < 0, 0, labels + 1)
    loss = optax.ctc_loss(logits, lp.astype(jnp.float32), labels,
                          pad.astype(jnp.float32))
    return loss


@register_op("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, *, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    return data
