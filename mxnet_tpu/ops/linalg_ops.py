"""Linear-algebra ops (the reference's la_op family).

Reference parity: src/operator/tensor/la_op.cc (linalg_gemm2, potrf, potri,
trsm, trmm, syrk, gelqf, syevd, ...) backed there by cuBLAS/LAPACK
(src/operator/linalg.h); here by jnp.linalg / lax.linalg which XLA lowers
to MXU-friendly blocked kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("_linalg_gemm2", aliases=("linalg_gemm2",))
def linalg_gemm2(a, b, *, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register_op("_linalg_gemm", aliases=("linalg_gemm",))
def linalg_gemm(a, b, c, *, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * c


@register_op("_linalg_potrf", aliases=("linalg_potrf",))
def linalg_potrf(a, *, lower=True):
    l = jnp.linalg.cholesky(a)
    if not lower:
        l = jnp.swapaxes(l, -1, -2)
    return l


@register_op("_linalg_potri", aliases=("linalg_potri",))
def linalg_potri(a, *, lower=True):
    """Inverse from Cholesky factor: inv(A) given L with A = L L^T."""
    linv = jax.scipy.linalg.solve_triangular(
        a, jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape),
        lower=lower)
    if lower:
        return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)
    return jnp.matmul(linv, jnp.swapaxes(linv, -1, -2))


@register_op("_linalg_trsm", aliases=("linalg_trsm",))
def linalg_trsm(a, b, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    if rightside:
        # solve X A = alpha B  ->  A^T X^T = alpha B^T
        xt = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * b, -1, -2),
            lower=not lower, trans=1 if transpose else 0)
        return jnp.swapaxes(xt, -1, -2)
    return jax.scipy.linalg.solve_triangular(
        a, alpha * b, lower=lower, trans=1 if transpose else 0)


@register_op("_linalg_trmm", aliases=("linalg_trmm",))
def linalg_trmm(a, b, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    if rightside:
        return alpha * jnp.matmul(b, tri)
    return alpha * jnp.matmul(tri, b)


@register_op("_linalg_syrk", aliases=("linalg_syrk",))
def linalg_syrk(a, *, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    if transpose:
        return alpha * jnp.matmul(at, a)
    return alpha * jnp.matmul(a, at)


@register_op("_linalg_gelqf", aliases=("linalg_gelqf",), num_outputs=2)
def linalg_gelqf(a):
    """LQ factorization: A = L Q (reference la_op gelqf)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register_op("_linalg_syevd", aliases=("linalg_syevd",), num_outputs=2)
def linalg_syevd(a):
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register_op("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def linalg_sumlogdiag(a):
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register_op("_linalg_extractdiag", aliases=("linalg_extractdiag",))
def linalg_extractdiag(a, *, offset=0):
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register_op("_linalg_makediag", aliases=("linalg_makediag",))
def linalg_makediag(a, *, offset=0):
    return _makediag(a, offset)


def _makediag(a, offset):
    n = a.shape[-1] + abs(offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    idx = jnp.arange(a.shape[-1])
    if offset >= 0:
        return out.at[..., idx, idx + offset].set(a)
    return out.at[..., idx - offset, idx].set(a)


@register_op("_linalg_inverse", aliases=("linalg_inverse", "inverse"))
def linalg_inverse(a):
    return jnp.linalg.inv(a)


@register_op("_linalg_det", aliases=("linalg_det", "det"))
def linalg_det(a):
    return jnp.linalg.det(a)


@register_op("_linalg_slogdet", aliases=("linalg_slogdet", "slogdet"),
             num_outputs=2)
def linalg_slogdet(a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet
