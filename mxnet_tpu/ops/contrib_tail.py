"""Contrib operator tail (round 3).

Reference parity: src/operator/contrib/sync_batch_norm.cc,
deformable_convolution.cc, bilinear_resize.cc, adaptive_avg_pooling.cc,
correlation.cc, count_sketch.cc and the interleaved multi-head
attention ops (transformer-inl.h).  TPU-native: everything is dense
jnp/lax — gathers ride the vector unit, contractions the MXU; SyncBN's
cross-device reduction is one ``lax.pmean`` over the mesh axis instead
of the reference's NCCL AllReduce key-value protocol.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


# ------------------------------------------------------ SyncBatchNorm
def _syncbn_nout(p):
    return 3 if p.get("output_mean_var") else 1


@register_op("_contrib_SyncBatchNorm", aliases=("SyncBatchNorm",),
             num_outputs=_syncbn_nout, train_param="train")
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, *,
                    eps=1e-3, momentum=0.9, fix_gamma=True,
                    use_global_stats=False, output_mean_var=False,
                    ndev=1, key=None, axis_name=None, train=False):
    """Reference: src/operator/contrib/sync_batch_norm.cc — BatchNorm
    whose batch statistics reduce across devices.

    Inside a ``shard_map``/``pmap`` over ``axis_name``, per-device
    sums ``lax.pmean`` into global statistics (the reference's
    cross-device AllReduce of sum/sumsq); without a mapped axis it
    degenerates to plain BatchNorm on the full batch.
    """
    ax = 1 % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = [1] * data.ndim
    bshape[ax] = data.shape[ax]
    x32 = data.astype(jnp.float32)
    if train and not use_global_stats:
        mean = jnp.mean(x32, axis=red)
        meansq = jnp.mean(x32 * x32, axis=red)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            meansq = lax.pmean(meansq, axis_name)
        var = jnp.maximum(meansq - mean * mean, 0.0)
    else:
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
    g32 = jnp.ones_like(mean) if fix_gamma else gamma.astype(jnp.float32)
    inv = lax.rsqrt(var + eps)
    out = ((x32 - mean.reshape(bshape)) * (inv * g32).reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape)).astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


# --------------------------------------------- DeformableConvolution
def _bilinear_gather(data, y, x):
    """data (C, H, W); y/x arbitrary same-shaped float coords; bilinear
    sample with zero padding outside."""
    c, h, w = data.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = y - y0
    wx = x - x0

    def tap(yy, xx):
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
        g = data[:, yc, xc]  # (C, *coord_shape)
        return g * valid.astype(data.dtype)

    return (tap(y0, x0) * ((1 - wy) * (1 - wx))
            + tap(y0, x0 + 1) * ((1 - wy) * wx)
            + tap(y0 + 1, x0) * (wy * (1 - wx))
            + tap(y0 + 1, x0 + 1) * (wy * wx))


@register_op("_contrib_DeformableConvolution",
             aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, *, kernel,
                           num_filter, stride=(1, 1), dilate=(1, 1),
                           pad=(0, 0), num_group=1,
                           num_deformable_group=1, no_bias=False,
                           workspace=1024, layout=None):
    """Reference: src/operator/contrib/deformable_convolution.cc
    (Dai et al., Deformable ConvNets).  Sampled patches gather with
    learned offsets, then one einsum onto the MXU."""
    n, c, h, w = data.shape
    kh, kw = kernel
    sh, sw = stride if isinstance(stride, (tuple, list)) else (stride,) * 2
    dh, dw = dilate if isinstance(dilate, (tuple, list)) else (dilate,) * 2
    ph, pw = pad if isinstance(pad, (tuple, list)) else (pad,) * 2
    ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wo = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    ndg = num_deformable_group

    # base sampling grid per kernel tap: (kh*kw, ho, wo)
    ys = jnp.arange(ho) * sh - ph
    xs = jnp.arange(wo) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = ys[None, :, None] + ky.repeat(kw)[:, None, None]
    base_x = xs[None, None, :] + jnp.tile(kx, kh)[:, None, None]
    base_y = jnp.broadcast_to(base_y, (kh * kw, ho, wo))
    base_x = jnp.broadcast_to(base_x, (kh * kw, ho, wo))

    # offset: (N, ndg*2*kh*kw, ho, wo) -> (N, ndg, kh*kw, 2, ho, wo)
    off = offset.reshape(n, ndg, kh * kw, 2, ho, wo)

    def sample_one(dat, off_b):
        # dat (C,H,W), off_b (ndg, kh*kw, 2, ho, wo)
        cg = c // ndg

        def per_group(dg, og):
            y = base_y + og[:, 0]
            x = base_x + og[:, 1]
            return _bilinear_gather(dg, y, x)  # (cg, kh*kw, ho, wo)

        groups = [per_group(dat[g * cg:(g + 1) * cg], off_b[g])
                  for g in range(ndg)]
        return jnp.concatenate(groups, axis=0)  # (C, kh*kw, ho, wo)

    cols = jax.vmap(sample_one)(data, off)  # (N, C, kh*kw, ho, wo)
    cg2 = c // num_group
    og2 = num_filter // num_group
    cols = cols.reshape(n, num_group, cg2, kh * kw, ho, wo)
    wr = weight.reshape(num_group, og2, cg2, kh * kw)
    out = jnp.einsum("ngckhw,gock->ngohw", cols, wr)
    out = out.reshape(n, num_filter, ho, wo)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ------------------------------------------------- BilinearResize2D
@register_op("_contrib_BilinearResize2D", aliases=("BilinearResize2D",))
def bilinear_resize_2d(data, *, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size"):
    """Reference: src/operator/contrib/bilinear_resize.cc —
    align-corners bilinear (x_src = x_dst*(W_in-1)/(W_out-1))."""
    from ..base import MXNetError

    n, c, h, w = data.shape
    if scale_height is not None and (not height and not width):
        if scale_width is None:
            scale_width = scale_height
        height = int(round(h * float(scale_height)))
        width = int(round(w * float(scale_width)))
    ho, wo = int(height), int(width)
    if ho <= 0 or wo <= 0:
        raise MXNetError(
            f"BilinearResize2D mode={mode!r}: resolved output size "
            f"({ho}, {wo}) is empty — pass height/width or "
            "scale_height/scale_width")
    ys = jnp.arange(ho) * ((h - 1) / max(ho - 1, 1))
    xs = jnp.arange(wo) * ((w - 1) / max(wo - 1, 1))
    y, x = jnp.meshgrid(ys, xs, indexing="ij")

    def one(dat):
        return _bilinear_gather(dat, y, x)

    return jax.vmap(one)(data).astype(data.dtype)


# --------------------------------------------- AdaptiveAvgPooling2D
@register_op("_contrib_AdaptiveAvgPooling2D",
             aliases=("AdaptiveAvgPooling2D",))
def adaptive_avg_pooling_2d(data, *, output_size=(1, 1)):
    """Reference: src/operator/contrib/adaptive_avg_pooling.cc — via an
    integral image so uneven bins stay one fused gather (no
    data-dependent loop for XLA)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ho, wo = output_size
    n, c, h, w = data.shape
    x32 = data.astype(jnp.float32)
    integ = jnp.pad(x32.cumsum(2).cumsum(3), ((0, 0), (0, 0), (1, 0),
                                              (1, 0)))
    import numpy as onp

    ys = onp.floor(onp.arange(ho) * h / ho).astype("int32")
    ye = onp.ceil((onp.arange(ho) + 1) * h / ho).astype("int32")
    xs = onp.floor(onp.arange(wo) * w / wo).astype("int32")
    xe = onp.ceil((onp.arange(wo) + 1) * w / wo).astype("int32")
    area = ((ye - ys)[:, None] * (xe - xs)[None, :]).astype("float32")
    s = (integ[:, :, ye[:, None], xe[None, :]]
         - integ[:, :, ys[:, None], xe[None, :]]
         - integ[:, :, ye[:, None], xs[None, :]]
         + integ[:, :, ys[:, None], xs[None, :]])
    return (s / area).astype(data.dtype)


# ---------------------------------------------------------- Correlation
@register_op("_contrib_Correlation", aliases=("Correlation",))
def correlation(data1, data2, *, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """Reference: src/operator/contrib/correlation.cc (FlowNet): for
    each displacement in the search window, the channel-mean of the
    patchwise product (or abs-difference) of the two feature maps."""
    n, c, h, w = data1.shape
    d = max_displacement
    p = pad_size
    a = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    # b gets an extra max_displacement of zero padding so edge
    # displacements read zeros instead of dynamic_slice silently
    # clamping back in bounds
    b = jnp.pad(data2, ((0, 0), (0, 0), (p + d, p + d), (p + d, p + d)))
    hp, wp = h + 2 * p, w + 2 * p
    k2 = kernel_size // 2
    ho = (hp - 2 * d - 2 * k2 + (stride1 - 1)) // stride1
    wo = (wp - 2 * d - 2 * k2 + (stride1 - 1)) // stride1
    disp = range(-d, d + 1, stride2)
    outs = []
    y0 = d + k2
    for dy in disp:
        for dx in disp:
            aa = lax.dynamic_slice(
                a, (0, 0, y0, y0),
                (n, c, ho * stride1, wo * stride1))
            bb = lax.dynamic_slice(
                b, (0, 0, y0 + dy + d, y0 + dx + d),
                (n, c, ho * stride1, wo * stride1))
            if kernel_size > 1:
                win = kernel_size
                prod = aa * bb if is_multiply else jnp.abs(aa - bb)
                prod = lax.reduce_window(
                    prod, 0.0, lax.add, (1, 1, win, win), (1, 1, 1, 1),
                    [(0, 0), (0, 0), (k2, k2), (k2, k2)])
                prod = prod / (win * win)
            else:
                prod = aa * bb if is_multiply else jnp.abs(aa - bb)
            outs.append(prod[:, :, ::stride1, ::stride1].mean(axis=1))
    return jnp.stack(outs, axis=1)


# --------------------------------------------------------- count_sketch
@register_op("_contrib_count_sketch", differentiable=False)
def count_sketch(data, h, s, *, out_dim, processing_batch_size=32):
    """Reference: src/operator/contrib/count_sketch.cc (compact
    bilinear pooling): out[:, h[i]] += s[i] * data[:, i]."""
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    contrib = data * sign[None, :]
    out = jnp.zeros((data.shape[0], int(out_dim)), data.dtype)
    return out.at[:, idx].add(contrib)


# ------------------------------------- interleaved multi-head attention
@register_op("_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, *, heads):
    """Reference: transformer-inl.h InterleavedMatMulSelfAttQK — input
    (L, B, heads*3*dim) with per-head interleaved [q; k; v]; output
    (B*heads, L, L) scaled q.k^T."""
    ln, b, e = queries_keys_values.shape
    d = e // heads // 3
    qkv = queries_keys_values.reshape(ln, b, heads, 3, d)
    q = qkv[:, :, :, 0]
    k = qkv[:, :, :, 1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)).astype(
        queries_keys_values.dtype)
    scores = jnp.einsum("lbhd,mbhd->bhlm", q * scale, k)
    return scores.reshape(b * heads, ln, ln)


@register_op("_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, *,
                                      heads):
    """Reference: InterleavedMatMulSelfAttValAtt — attention
    (B*heads, L, L) applied to the interleaved values; output
    (L, B, heads*dim)."""
    ln, b, e = queries_keys_values.shape
    d = e // heads // 3
    v = queries_keys_values.reshape(ln, b, heads, 3, d)[:, :, :, 2]
    att = attention.reshape(b, heads, ln, ln)
    out = jnp.einsum("bhlm,mbhd->lbhd", att, v)
    return out.reshape(ln, b, heads * d)


@register_op("_contrib_interleaved_matmul_encdec_qk")
def interleaved_matmul_encdec_qk(queries, keys_values, *, heads):
    """Reference: InterleavedMatMulEncDecQK — queries (Lq, B, heads*dim),
    keys_values (Lk, B, heads*2*dim) interleaved [k; v]; output
    (B*heads, Lq, Lk)."""
    lq, b, eq = queries.shape
    d = eq // heads
    lk = keys_values.shape[0]
    q = queries.reshape(lq, b, heads, d)
    k = keys_values.reshape(lk, b, heads, 2, d)[:, :, :, 0]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)).astype(
        queries.dtype)
    scores = jnp.einsum("lbhd,mbhd->bhlm", q * scale, k)
    return scores.reshape(b * heads, lq, lk)


@register_op("_contrib_interleaved_matmul_encdec_valatt")
def interleaved_matmul_encdec_valatt(keys_values, attention, *, heads):
    """Reference: InterleavedMatMulEncDecValAtt."""
    lk, b, e = keys_values.shape
    d = e // heads // 2
    v = keys_values.reshape(lk, b, heads, 2, d)[:, :, :, 1]
    lq = attention.shape[1]
    att = attention.reshape(b, heads, lq, lk)
    out = jnp.einsum("bhlm,mbhd->lbhd", att, v)
    return out.reshape(lq, b, heads * d)
