"""Reductions and index reductions.

Reference parity: src/operator/tensor/broadcast_reduce_op*.{h,cc,cu}
(sum/mean/prod/nansum/nanprod/max/min/norm with axis/keepdims/exclude) and
ordering ops argmax/argmin (SURVEY.md §2.3 `tensor/`).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _axes(axis, ndim, exclude=False):
    if axis is None or axis == ():
        return None  # reduce over everything
    if isinstance(axis, int):
        axis = (axis,)
    ax = tuple(sorted(a % ndim for a in axis))
    if exclude:
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def _reduce(fn_name):
    f = getattr(jnp, fn_name)

    def op(x, *, axis=None, keepdims=False, exclude=False):
        ax = _axes(axis, x.ndim, exclude)
        return f(x, axis=ax, keepdims=keepdims)

    return op


for _n, _jn in [("sum", "sum"), ("mean", "mean"), ("prod", "prod"),
                ("nansum", "nansum"), ("nanprod", "nanprod"),
                ("max", "max"), ("min", "min")]:
    register_op(_n, aliases=(f"{_n}_axis",))(_reduce(_jn))


@register_op("norm")
def norm(x, *, ord=2, axis=None, keepdims=False, out_dtype=None):
    if isinstance(axis, int):
        axis = (axis,)
    if ord == 1:
        r = jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
    else:
        r = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))
    if out_dtype is not None:
        from ..dtype import normalize_dtype

        r = r.astype(normalize_dtype(out_dtype))
    return r


def _index_reduce(f):
    def op(x, *, axis=None, keepdims=False):
        if axis is None:
            return f(x.reshape(-1)).astype(jnp.float32)
        r = f(x, axis=int(axis))
        if keepdims:
            r = jnp.expand_dims(r, int(axis))
        return r.astype(jnp.float32)

    return op


register_op("argmax", differentiable=False)(_index_reduce(jnp.argmax))
register_op("argmin", differentiable=False)(_index_reduce(jnp.argmin))


@register_op("argmax_channel", differentiable=False)
def argmax_channel(x):
    return jnp.argmax(x, axis=1).astype(jnp.float32)


@register_op("cumsum", aliases=("_np_cumsum",))
def cumsum(x, *, axis=None, dtype=None):
    from ..dtype import normalize_dtype

    if dtype is not None:
        x = x.astype(normalize_dtype(dtype))
    if axis is None:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis=axis)


@register_op("moments", num_outputs=2)
def moments(x, *, axes=None, keepdims=False):
    """Reference: src/operator/nn/moments.cc."""
    if isinstance(axes, int):
        axes = (axes,)
    mean = jnp.mean(x, axis=axes, keepdims=keepdims)
    var = jnp.var(x, axis=axes, keepdims=keepdims)
    return mean, var
