"""Contrib operators: AMP support, boolean masking, FFT, index ops.

Reference parity: src/operator/contrib/ — all_finite.cc (AMP dynamic
loss scaling), boolean_mask.cc, fft/ifft.cc, index_copy.cc,
allclose_op.cc, gradientmultiplier_op.cc, hawkes_ll.cc.  Dynamic-shape
outputs (boolean_mask) use the fixed-size+mask XLA idiom documented in
SURVEY.md §7 hard parts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("all_finite", differentiable=False)
def all_finite(data, *, init_output=True):
    """Reference: src/operator/contrib/all_finite.cc — scalar 1.0 when
    every element is finite, else 0.0 (feeds AMP loss-scale logic)."""
    return jnp.isfinite(data).all().astype(jnp.float32).reshape(1)


@register_op("multi_all_finite", differentiable=False)
def multi_all_finite(*arrays, num_arrays=1, init_output=True):
    """Reference: all_finite.cc multi-tensor variant."""
    ok = jnp.array(True)
    for a in arrays:
        ok = ok & jnp.isfinite(a).all()
    return ok.astype(jnp.float32).reshape(1)


@register_op("_contrib_boolean_mask", aliases=("boolean_mask",))
def boolean_mask(data, index, *, axis=0):
    """Reference: src/operator/contrib/boolean_mask.cc.

    XLA needs static shapes, so the TPU-native contract is
    fixed-size+mask: selected rows are compacted to the FRONT of an
    output the same size as the input; the tail is zero-padded.  The
    number of valid rows equals ``index.sum()`` (host-checkable).
    """
    idx = index.astype(bool)
    n = data.shape[axis]
    order = jnp.argsort(~idx, stable=True)  # selected first, stable
    gathered = jnp.take(data, order, axis=axis)
    keep = jnp.arange(n) < idx.sum()
    shape = [1] * data.ndim
    shape[axis] = n
    return gathered * keep.reshape(shape).astype(data.dtype)


@register_op("_contrib_index_copy", differentiable=False)
def index_copy(old, idx, new_tensor):
    """Reference: src/operator/contrib/index_copy.cc."""
    return old.at[idx.astype(jnp.int32)].set(new_tensor)


@register_op("_contrib_index_array", differentiable=False)
def index_array(data, *, axes=None):
    """Reference: src/operator/contrib/index_array.cc — per-element
    coordinates."""
    shape = data.shape
    axes = tuple(range(len(shape))) if axes is None else tuple(axes)
    grids = jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij")
    return jnp.stack([grids[a] for a in axes], axis=-1).astype(jnp.int64)


@register_op("_contrib_fft", differentiable=False)
def fft(data, *, compute_size=128):
    """Reference: src/operator/contrib/fft/fft.cc — complex output packed
    as interleaved (real, imag) along the last axis, like cuFFT."""
    out = jnp.fft.fft(data.astype(jnp.float32))
    return jnp.stack([out.real, out.imag], axis=-1).reshape(
        *data.shape[:-1], 2 * data.shape[-1])


@register_op("_contrib_ifft", differentiable=False)
def ifft(data, *, compute_size=128):
    """Reference: fft/ifft.cc — input interleaved (real, imag)."""
    n = data.shape[-1] // 2
    pairs = data.reshape(*data.shape[:-1], n, 2)
    comp = pairs[..., 0] + 1j * pairs[..., 1]
    return jnp.fft.ifft(comp).real.astype(jnp.float32) * n


@register_op("_contrib_allclose", differentiable=False)
def allclose(a, b, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    """Reference: src/operator/contrib/allclose_op.cc."""
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).astype(jnp.float32).reshape(1)


@register_op("_contrib_gradientmultiplier")
def gradientmultiplier(data, *, scalar=1.0):
    """Reference: src/operator/contrib/gradientmultiplier_op.cc —
    identity forward, gradient scaled by ``scalar``."""

    @jax.custom_vjp
    def _f(x):
        return x

    def _fwd(x):
        return x, None

    def _bwd(_, ct):
        return (ct * scalar,)

    _f.defvjp(_fwd, _bwd)
    return _f(data)


@register_op("_contrib_hawkesll", num_outputs=2)
def hawkesll(mu, alpha, beta, state, lags, marks, valid_length, max_time):
    """Reference: src/operator/contrib/hawkes_ll-inl.h:119-185.

    Marked self-exciting Hawkes process log-likelihood.  Per valid event
    with inter-arrival gap d: intensity lambda_k = mu_k +
    alpha_k*beta_k*state_k*exp(-beta_k*d); the per-gap compensator is
    sum_k [mu_k*d + alpha_k*state_k*(1-exp(-beta_k*d))] (:149), and the
    remaining compensator integrates from the last event to max_time
    (:184).  Returns (ll per sample, decayed state at max_time).
    """
    mu = mu.astype(jnp.float32)
    k = mu.shape[-1]
    n, t = lags.shape
    marks_i = marks.astype(jnp.int32)
    valid = (jnp.arange(t)[None, :] < valid_length.reshape(-1, 1))

    def scan_body(carry, inp):
        st, ll, elapsed = carry
        lag, mark, is_valid = inp
        d = (lag * is_valid).reshape(-1, 1)
        ed = jnp.exp(-beta * d)
        decayed = st * ed
        lam = mu + alpha * beta * decayed
        lam_m = jnp.take_along_axis(lam, mark.reshape(-1, 1), axis=1)[:, 0]
        comp = (mu * d + alpha * st * (1 - ed)).sum(-1)
        ll = ll + jnp.where(is_valid, jnp.log(lam_m + 1e-30) - comp, 0.0)
        add = jax.nn.one_hot(mark, k, dtype=mu.dtype) * \
            is_valid[:, None].astype(mu.dtype)
        st = decayed + add
        elapsed = elapsed + d[:, 0]
        return (st, ll, elapsed), None

    st0 = state.astype(jnp.float32)
    ll0 = jnp.zeros((n,), jnp.float32)
    (st, ll, elapsed), _ = jax.lax.scan(
        scan_body, (st0, ll0, jnp.zeros((n,), jnp.float32)),
        (lags.T.astype(jnp.float32), marks_i.T, valid.T.astype(bool)))
    d_rem = jnp.maximum(max_time.reshape(-1, 1) - elapsed[:, None], 0.0)
    ed_rem = jnp.exp(-beta * d_rem)
    rem_comp = (mu * d_rem + alpha * st * (1 - ed_rem)).sum(-1)
    return ll - rem_comp, st * ed_rem
