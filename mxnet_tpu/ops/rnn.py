"""Fused multi-layer RNN op (LSTM / GRU / vanilla RNN).

Reference parity: src/operator/rnn-inl.h:414 (``RNNOp``) — the reference
dispatches to cuDNN (CUDNN_LSTM etc., rnn-inl.h:444-476) with a packed flat
parameter vector; CPU fallback in rnn_impl.h.  TPU-native redesign: one
``lax.scan`` per (layer, direction) — scan keeps the time loop inside the
compiled program (no per-step dispatch), and each step is a fused
(batch, 4H) matmul on the MXU.

Weight packing follows the reference/cuDNN convention so checkpoints can be
transliterated: for each layer, for each direction: W_i2h (G*H, in),
W_h2h (G*H, H); then for each layer/direction: b_i2h (G*H), b_h2h (G*H).
Gate order: LSTM [i, f, g, o]; GRU [r, z, n].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def unpack_rnn_params(params, mode, num_layers, input_size, state_size,
                      bidirectional=False, projection_size=None):
    """Split the flat parameter vector into per-layer weight/bias arrays.

    With ``projection_size=r`` (LSTMP, reference rnn-inl.h:444-476) the
    recurrent input is the projected hidden of size r: per layer/dir
    W_i2h (G*H, in), W_h2h (G*H, r), W_proj (r, H); biases unchanged.
    """
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    r = projection_size if projection_size else h
    ws, bs = [], []
    off = 0
    for layer in range(num_layers):
        ins = input_size if layer == 0 else r * d
        for _ in range(d):
            w_i2h = params[off:off + g * h * ins].reshape(g * h, ins)
            off += g * h * ins
            w_h2h = params[off:off + g * h * r].reshape(g * h, r)
            off += g * h * r
            if projection_size:
                w_proj = params[off:off + r * h].reshape(r, h)
                off += r * h
            else:
                w_proj = None
            ws.append((w_i2h, w_h2h, w_proj))
    for layer in range(num_layers):
        for _ in range(d):
            b_i2h = params[off:off + g * h]
            off += g * h
            b_h2h = params[off:off + g * h]
            off += g * h
            bs.append((b_i2h, b_h2h))
    return ws, bs


def rnn_param_size(mode, num_layers, input_size, state_size,
                   bidirectional=False, projection_size=None):
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    r = projection_size if projection_size else h
    size = 0
    for layer in range(num_layers):
        ins = input_size if layer == 0 else r * d
        size += d * (g * h * ins + g * h * r + 2 * g * h)
        if projection_size:
            size += d * r * h
    return size


def _cell_step(mode, w_i2h, w_h2h, b_i2h, b_h2h, x, h_prev, c_prev,
               w_proj=None):
    gi = jnp.dot(x, w_i2h.T) + b_i2h
    gh = jnp.dot(h_prev, w_h2h.T) + b_h2h
    if mode == "lstm":
        z = gi + gh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        if w_proj is not None:  # LSTMP recurrent projection
            h = jnp.dot(h, w_proj.T)
        return h, c
    if mode == "gru":
        ri, zi, ni = jnp.split(gi, 3, axis=-1)
        rh, zh, nh = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ri + rh)
        z = jax.nn.sigmoid(zi + zh)
        n = jnp.tanh(ni + r * nh)
        return (1 - z) * n + z * h_prev, c_prev
    act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh
    return act(gi + gh), c_prev


def _run_layer(mode, wb, x, h0, c0, reverse=False):
    (w_i2h, w_h2h, w_proj), (b_i2h, b_h2h) = wb

    def step(carry, xt):
        h_prev, c_prev = carry
        h, c = _cell_step(mode, w_i2h, w_h2h, b_i2h, b_h2h, xt, h_prev,
                          c_prev, w_proj)
        return (h, c), h

    (hT, cT), ys = jax.lax.scan(step, (h0, c0), x, reverse=reverse)
    return ys, hT, cT


def _rnn_nout(p):
    n = 1
    if p.get("state_outputs", False):
        n += 2 if p.get("mode", "lstm") == "lstm" else 1
    return n


@register_op("RNN", num_outputs=_rnn_nout, key_param="key",
             train_param="train")
def rnn(data, parameters, state, state_cell=None, *, state_size, num_layers,
        mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
        projection_size=None, lstm_state_clip_min=None,
        lstm_state_clip_max=None, lstm_state_clip_nan=False,
        use_sequence_length=False, key=None, train=False):
    """data: (T, N, I); state: (L*dir, N, H). Returns output (T, N, H*dir)
    [+ final h [+ final c for lstm] when state_outputs]."""
    t, n, input_size = data.shape
    d = 2 if bidirectional else 1
    if projection_size is not None and mode != "lstm":
        raise ValueError("projection_size is LSTM-only (rnn-inl.h:444)")
    ws, bs = unpack_rnn_params(parameters, mode, num_layers, input_size,
                               state_size, bidirectional,
                               projection_size)
    x = data
    h_fin, c_fin = [], []
    for layer in range(num_layers):
        outs = []
        for direction in range(d):
            idx = layer * d + direction
            h0 = state[idx]
            if mode == "lstm" and state_cell is not None:
                c0 = state_cell[idx]
            elif projection_size is not None:
                c0 = jnp.zeros((n, state_size), h0.dtype)
            else:
                c0 = jnp.zeros_like(h0)
            ys, hT, cT = _run_layer(mode, (ws[idx], bs[idx]), x, h0, c0,
                                    reverse=(direction == 1))
            outs.append(ys)
            h_fin.append(hT)
            c_fin.append(cT)
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        if train and p > 0 and layer < num_layers - 1 and key is not None:
            sub = jax.random.fold_in(key, layer)
            mask = jax.random.bernoulli(sub, 1 - p, x.shape)
            x = jnp.where(mask, x / (1 - p), 0).astype(x.dtype)
        if mode == "lstm" and lstm_state_clip_min is not None:
            c_fin = [jnp.clip(c, lstm_state_clip_min, lstm_state_clip_max)
                     for c in c_fin]
    if not state_outputs:
        return x
    hs = jnp.stack(h_fin)
    if mode == "lstm":
        return x, hs, jnp.stack(c_fin)
    return x, hs
