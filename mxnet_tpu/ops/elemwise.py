"""Elementwise unary / binary / scalar operator families.

Reference parity: src/operator/tensor/elemwise_{unary,binary,binary_scalar,
binary_broadcast}_op*.{cc,cu} and the mshadow_op functor zoo
(src/operator/mshadow_op.h) — ~35k LoC of CUDA/C++ that collapses to jnp
one-liners here because XLA owns codegen and fusion (SURVEY.md §7: the
pointwise-fusion pass src/executor/pointwise_fusion_pass.cc is obsolete on
XLA, which fuses elementwise chains into neighboring MXU ops natively).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

_f32 = jnp.float32


def _promote_bool(x):
    return x.astype(jnp.int32) if x.dtype == jnp.bool_ else x


# --------------------------------------------------------------- unary
_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "negative": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "erf": jax.lax.erf,
    "erfinv": jax.lax.erf_inv,
    "gamma": lambda x: jnp.exp(jax.lax.lgamma(x)),
    "gammaln": jax.lax.lgamma,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
}

for _name, _f in _UNARY.items():
    register_op(_name, aliases=(f"_np_{_name}",))(
        (lambda f: lambda x: f(x))(_f)
    )


@register_op("_copy", aliases=("identity",))
def _copy(x):
    return x


@register_op("BlockGrad", aliases=("stop_gradient",))
def block_grad(x):
    """Reference: src/operator/tensor/elemwise_unary_op_basic.cc BlockGrad."""
    return jax.lax.stop_gradient(x)


@register_op("make_loss")
def make_loss(x):
    """Reference make_loss: gradient of ones (src/operator/make_loss.cc)."""
    return x


@register_op("zeros_like")
def zeros_like(x):
    return jnp.zeros_like(x)


@register_op("ones_like")
def ones_like(x):
    return jnp.ones_like(x)


@register_op("clip")
def clip(x, *, a_min, a_max):
    return jnp.clip(x, a_min, a_max)


@register_op("smooth_l1")
def smooth_l1(x, *, scalar=1.0):
    """Reference: src/operator/tensor/elemwise_binary_scalar_op_extended.cc."""
    s2 = scalar * scalar
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)


# --------------------------------------------------------------- binary
def _true_div(a, b):
    if jnp.issubdtype(jnp.result_type(a, b), jnp.integer):
        return (a / b).astype(jnp.result_type(a, b))
    return a / b


_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": _true_div,
    "mod": jnp.fmod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "logical_and": lambda a, b: jnp.logical_and(a != 0, b != 0).astype(a.dtype),
    "logical_or": lambda a, b: jnp.logical_or(a != 0, b != 0).astype(a.dtype),
    "logical_xor": lambda a, b: jnp.logical_xor(a != 0, b != 0).astype(a.dtype),
}

_BINARY_ALIASES = {
    "add": ("elemwise_add", "_plus", "_add"),
    "sub": ("elemwise_sub", "_minus", "_sub"),
    "mul": ("elemwise_mul", "_mul"),
    "div": ("elemwise_div", "_div"),
    "mod": ("_mod",),
    "power": ("_power",),
    "maximum": ("_maximum",),
    "minimum": ("_minimum",),
    "hypot": ("_hypot",),
    "logical_and": ("_logical_and",),
    "logical_or": ("_logical_or",),
    "logical_xor": ("_logical_xor",),
}

for _name, _f in _BINARY.items():
    # broadcast_* and elemwise_* share impls: XLA broadcasting covers both
    register_op(f"broadcast_{_name}", aliases=_BINARY_ALIASES[_name])(
        (lambda f: lambda a, b: f(a, b))(_f)
    )

_CMP = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "lesser": jnp.less,
    "lesser_equal": jnp.less_equal,
}

for _name, _f in _CMP.items():
    register_op(f"broadcast_{_name}", aliases=(f"_{_name}",),
                differentiable=False)(
        (lambda f: lambda a, b: f(a, b).astype(_f32))(_f)
    )


@register_op("_hypot_scalar")
def _hypot_scalar(x, *, scalar):
    return jnp.hypot(x, scalar)


# --------------------------------------------------------------- scalar
_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.fmod(x, s),
    "_rmod_scalar": lambda x, s: jnp.fmod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
}

for _name, _f in _SCALAR.items():
    register_op(_name)(
        (lambda f: lambda x, *, scalar: f(
            x, jnp.asarray(scalar, dtype=x.dtype
                           if jnp.issubdtype(x.dtype, jnp.floating)
                           else jnp.result_type(x.dtype, type(scalar)))))(_f)
    )

_SCALAR_CMP = {
    "_equal_scalar": jnp.equal,
    "_not_equal_scalar": jnp.not_equal,
    "_greater_scalar": jnp.greater,
    "_greater_equal_scalar": jnp.greater_equal,
    "_lesser_scalar": jnp.less,
    "_lesser_equal_scalar": jnp.less_equal,
}

for _name, _f in _SCALAR_CMP.items():
    register_op(_name, differentiable=False)(
        (lambda f: lambda x, *, scalar: f(x, scalar).astype(_f32))(_f)
    )


@register_op("add_n", aliases=("ElementWiseSum",))
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register_op("Cast", aliases=("cast",))
def cast(x, *, dtype):
    from ..dtype import normalize_dtype

    return x.astype(normalize_dtype(dtype))


@register_op("amp_cast")
def amp_cast(x, *, dtype):
    from ..dtype import normalize_dtype

    return x.astype(normalize_dtype(dtype))


@register_op("amp_multicast", num_outputs=lambda p: p.get("num_outputs", 1))
def amp_multicast(*args, num_outputs):
    """Cast all inputs to the widest input dtype (reference
    src/operator/tensor/amp_cast.cc)."""
    widest = jnp.result_type(*[a.dtype for a in args])
    return tuple(a.astype(widest) for a in args)


@register_op("where")
def where(condition, x, y):
    return jnp.where(condition != 0, x, y)


@register_op("_getitem")
def _getitem(x, *, key):
    return x[key]


