"""Shape manipulation, indexing, gather/scatter, and matmul family.

Reference parity: src/operator/tensor/matrix_op*.{cc,cu} (reshape/transpose/
slice/take/tile/repeat/pad/...), dot (src/operator/tensor/dot-inl.h),
indexing ops (gather_nd/scatter_nd), Embedding
(src/operator/tensor/indexing_op.cc) — SURVEY.md §2.3 `tensor/`.
Dense matmuls route to the MXU via jnp.dot/einsum; XLA tiles them.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from .registry import register_op


@register_op("Reshape", aliases=("reshape",))
def reshape(x, *, shape=None, reverse=False):
    """Supports the reference's special codes 0 / -1 / -2 / -3 / -4 and
    reverse=True right-to-left matching (matrix_op.cc Reshape docs)."""
    if shape is None:
        return x
    if reverse:
        # reference algorithm (matrix_op-inl.h InferReshapeShape:96-165):
        # reverse dims and spec, run the same left-to-right resolution,
        # reverse the result — e.g. (10,5,4) with (-1,0) -> (50,4)
        tgt = _resolve_reshape_spec(list(x.shape)[::-1],
                                    list(shape)[::-1])[::-1]
        return jnp.reshape(x, tuple(tgt))
    tgt = _resolve_reshape_spec(list(x.shape), list(shape))
    return jnp.reshape(x, tuple(tgt))


def _resolve_reshape_spec(src, shape):
    out = []
    i = 0  # index into src
    j = 0
    while j < len(shape):
        d = shape[j]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            a, b = shape[j + 1], shape[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(d); i += 1
        j += 1
    return out  # a -1 entry is resolved by jnp.reshape


@register_op("reshape_like")
def reshape_like(x, y):
    return jnp.reshape(x, y.shape)


@register_op("Flatten", aliases=("flatten",))
def flatten(x):
    return jnp.reshape(x, (x.shape[0], -1))


@register_op("transpose")
def transpose(x, *, axes=None):
    if axes is None or axes == ():
        axes = tuple(reversed(range(x.ndim)))
    return jnp.transpose(x, axes)


@register_op("expand_dims")
def expand_dims(x, *, axis):
    return jnp.expand_dims(x, axis)


@register_op("squeeze")
def squeeze(x, *, axis=None):
    return jnp.squeeze(x, axis)


@register_op("swapaxes", aliases=("SwapAxis",))
def swapaxes(x, *, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register_op("flip", aliases=("reverse",))
def flip(x, *, axis):
    return jnp.flip(x, axis)


@register_op("tile")
def tile(x, *, reps):
    return jnp.tile(x, reps)


@register_op("repeat")
def repeat(x, *, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register_op("Pad", aliases=("pad",))
def pad(x, *, mode="constant", pad_width=None, constant_value=0.0):
    """Reference: src/operator/pad.cc — pad_width is 2*ndim flat list."""
    pw = [(int(pad_width[2 * i]), int(pad_width[2 * i + 1]))
          for i in range(x.ndim)]
    if mode == "constant":
        return jnp.pad(x, pw, constant_values=constant_value)
    return jnp.pad(x, pw, mode={"edge": "edge", "reflect": "reflect"}[mode])


@register_op("broadcast_to")
def broadcast_to(x, *, shape):
    shape = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@register_op("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(x, *, axis=None, size=None):
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    tgt = list(x.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register_op("broadcast_like")
def broadcast_like(x, y, *, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(x, y.shape)
    tgt = list(x.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la] = y.shape[ra]
    return jnp.broadcast_to(x, tuple(tgt))


@register_op("slice", aliases=("crop",))
def slice_op(x, *, begin, end, step=None):
    idx = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return x[tuple(idx)]


def builtins_slice(b, e, s):
    return slice(b, e, s)


@register_op("slice_axis")
def slice_axis(x, *, axis, begin, end):
    idx = [slice(None)] * x.ndim
    if end is not None and end < 0:
        end = x.shape[axis] + end
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register_op("slice_like")
def slice_like(x, y, *, axes=()):
    axes = axes or tuple(range(min(x.ndim, y.ndim)))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, y.shape[a])
    return x[tuple(idx)]


@register_op("take")
def take(x, indices, *, axis=0, mode="clip"):
    # int32 indexing is the fast path; axes past 2^31-1 elements need
    # int64 offsets (the reference's MXNET_LARGE_TENSOR build; here the
    # large-tensor tier runs under JAX x64 — tests/test_large_array.py)
    big = x.shape[axis % x.ndim] > 2 ** 31 - 1
    idx = indices.astype(jnp.int64 if big else jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, x.shape[axis])
        mode = "clip"
    return jnp.take(x, idx, axis=axis, mode="clip")


@register_op("batch_take")
def batch_take(x, indices):
    return jnp.take_along_axis(
        x, indices.astype(jnp.int32)[:, None], axis=1
    )[:, 0]


@register_op("pick")
def pick(x, indices, *, axis=-1, keepdims=False, mode="clip"):
    idx = indices.astype(jnp.int32)
    idxe = jnp.expand_dims(idx, axis if axis >= 0 else x.ndim + axis)
    r = jnp.take_along_axis(x, idxe, axis=axis)
    if not keepdims:
        r = jnp.squeeze(r, axis=axis)
    return r


@register_op("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register_op("scatter_nd")
def scatter_nd(data, indices, *, shape):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(tuple(shape), data.dtype)
    return out.at[idx].add(data)


@register_op("one_hot", differentiable=False)
def one_hot(indices, *, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..dtype import normalize_dtype

    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    return (oh * (on_value - off_value) + off_value).astype(
        normalize_dtype(dtype))


@register_op("Embedding")
def embedding(data, weight, *, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):
    """Reference: src/operator/tensor/indexing_op.cc Embedding."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register_op("Concat", aliases=("concat",))
def concat_op(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=dim)


@register_op("rnn_param_concat")
def rnn_param_concat(*args, dim=0, num_args=None):
    return jnp.concatenate([a.reshape(-1) for a in args], axis=0)


@register_op("stack")
def stack_op(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


def _split_count(p):
    return int(p.get("num_outputs", 1))


@register_op("SliceChannel", aliases=("split",), num_outputs=_split_count)
def slice_channel(x, *, num_outputs, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register_op("split_v2", num_outputs=lambda p: p["_num"])
def split_v2(x, *, indices, axis=0, squeeze_axis=False, _num=None):
    parts = jnp.split(x, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register_op("depth_to_space")
def depth_to_space(x, *, block_size):
    n, c, h, w = x.shape
    b = block_size
    y = x.reshape(n, b, b, c // (b * b), h, w)
    y = y.transpose(0, 3, 4, 1, 5, 2)
    return y.reshape(n, c // (b * b), h * b, w * b)


@register_op("space_to_depth")
def space_to_depth(x, *, block_size):
    n, c, h, w = x.shape
    b = block_size
    y = x.reshape(n, c, h // b, b, w // b, b)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return y.reshape(n, c * b * b, h // b, w // b)


@register_op("diag")
def diag(x, *, k=0, axis1=0, axis2=1):
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=axis1, axis2=axis2)


@register_op("shape_array", differentiable=False)
def shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@register_op("size_array", differentiable=False)
def size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int64)


# ------------------------------------------------------------- matmul
@register_op("dot")
def dot(lhs, rhs, *, transpose_a=False, transpose_b=False,
        forward_stype=None):
    """Reference semantics (tensor/dot-inl.h): contract last axis of lhs
    with first axis of rhs; transpose flags reverse all axes first."""
    if transpose_a:
        lhs = jnp.transpose(lhs)
    if transpose_b:
        rhs = jnp.transpose(rhs)
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=1)


@register_op("batch_dot")
def batch_dot(lhs, rhs, *, transpose_a=False, transpose_b=False,
              forward_stype=None):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register_op("_npi_matmul", aliases=("matmul",))
def matmul(a, b):
    return jnp.matmul(a, b)


@register_op("khatri_rao")
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            out.shape[0] * m.shape[0], *out.shape[1:])
    return out
