"""Sequence ops: SequenceMask / SequenceLast / SequenceReverse.

Reference parity: src/operator/sequence_mask.cc, sequence_last.cc,
sequence_reverse.cc (SURVEY.md §2.3 "Sequence & misc").  Layout is the
reference's: time-major (T, N, ...) with optional per-batch lengths.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _len_mask(x, seq_len):
    t = x.shape[0]
    pos = jnp.arange(t)[:, None]
    return pos < seq_len[None, :].astype(jnp.int32)


@register_op("SequenceMask")
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    x = jnp.swapaxes(data, 0, axis) if axis != 0 else data
    m = _len_mask(x, sequence_length)
    m = m.reshape(m.shape + (1,) * (x.ndim - 2))
    out = jnp.where(m, x, jnp.asarray(value, x.dtype))
    return jnp.swapaxes(out, 0, axis) if axis != 0 else out


@register_op("SequenceLast")
def sequence_last(data, sequence_length=None, *, use_sequence_length=False,
                  axis=0):
    x = jnp.swapaxes(data, 0, axis) if axis != 0 else data
    if not use_sequence_length or sequence_length is None:
        return x[-1]
    idx = (sequence_length.astype(jnp.int32) - 1)
    idx = idx.reshape((1, -1) + (1,) * (x.ndim - 2))
    idx = jnp.broadcast_to(idx, (1,) + x.shape[1:])
    return jnp.take_along_axis(x, idx, axis=0)[0]


@register_op("SequenceReverse")
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    t = data.shape[0]
    lens = sequence_length.astype(jnp.int32)
    pos = jnp.arange(t)[:, None]
    # within-length positions are mirrored, the rest stay in place
    rev = jnp.where(pos < lens[None, :], lens[None, :] - 1 - pos, pos)
    rev = rev.reshape(rev.shape + (1,) * (data.ndim - 2))
    rev = jnp.broadcast_to(rev, data.shape)
    return jnp.take_along_axis(data, rev, axis=0)
