"""Control-flow operators: foreach / while_loop / cond.

Reference parity: src/operator/control_flow.cc:1089-1255 (_foreach,
_while_loop, _cond higher-order ops executing subgraph Symbols) and the
python surface mx.nd.contrib.foreach/while_loop/cond
(python/mxnet/ndarray/contrib.py).

TPU-native design: under jit tracing the bodies lower to lax.scan /
lax.while_loop / lax.cond — compiler-friendly control flow with no
Python in the loop.  Under eager autograd recording, the loop runs as a
taped Python loop instead (lax.while_loop is not reverse-mode
differentiable; the unrolled tape is, exactly like the reference's
imperative path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError


def _to_nd(x):
    from ..ndarray.ndarray import NDArray

    return x if isinstance(x, NDArray) else NDArray(jnp.asarray(x))


def _data(x):
    from ..ndarray.ndarray import NDArray

    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _states_list(states):
    single = not isinstance(states, (list, tuple))
    return ([states] if single else list(states)), single


def foreach(body, data, init_states):
    """Scan ``body(data_slice, states) -> (out, new_states)`` over axis 0
    of ``data`` (reference control_flow.cc _foreach).

    Eager+recording: taped Python loop.  Otherwise: lax.scan (one
    compiled loop, O(1) program size in sequence length).
    """
    from .. import autograd
    from ..ndarray.ndarray import NDArray

    datas, data_single = _states_list(data)
    states, states_single = _states_list(init_states)
    n = datas[0].shape[0]

    if autograd.is_recording():
        outs = []
        cur = [_to_nd(s) for s in states]
        for i in range(n):
            sl = [d[i] for d in datas]
            o, cur = body(sl[0] if data_single else sl,
                          cur[0] if states_single else cur)
            cur, _ = _states_list(cur)
            outs.append(o)
        from ..ndarray.ndarray import stack as nd_stack

        if isinstance(outs[0], (list, tuple)):
            stacked = [nd_stack(*[o[k] for o in outs], axis=0)
                       for k in range(len(outs[0]))]
        else:
            stacked = nd_stack(*outs, axis=0)
        return stacked, (cur[0] if states_single else cur)

    def scan_body(carry, xs):
        sl = [NDArray(x) for x in xs]
        st = [NDArray(c) for c in carry]
        o, new_st = body(sl[0] if data_single else sl,
                         st[0] if states_single else st)
        new_st, _ = _states_list(new_st)
        o_list, o_single = _states_list(o)
        return (tuple(_data(s) for s in new_st),
                tuple(_data(x) for x in o_list))

    carry, ys = lax.scan(scan_body, tuple(_data(s) for s in states),
                         tuple(_data(d) for d in datas))
    outs = [NDArray(y) for y in ys]
    states_out = [NDArray(c) for c in carry]
    out = outs[0] if len(outs) == 1 else outs
    return out, (states_out[0] if states_single else states_out)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Reference control_flow.cc _while_loop: run ``func`` while ``cond``
    holds, stacking per-step outputs padded to ``max_iterations``.

    Returns (outputs, final_loop_vars).  Python loop (the reference's
    imperative semantics — step outputs make the trip count data-
    dependent, which XLA cannot express with stacked outputs; loops
    without outputs should use lax.while_loop directly).
    """
    from ..ndarray.ndarray import NDArray, stack as nd_stack, zeros

    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    vars_, single = _states_list(loop_vars)
    vars_ = [_to_nd(v) for v in vars_]
    outs = []
    steps = 0
    while steps < max_iterations:
        c = cond(vars_[0] if single else vars_)
        c_val = bool(c.asnumpy().reshape(()) if isinstance(c, NDArray)
                     else c)
        if not c_val:
            break
        o, new_vars = func(vars_[0] if single else vars_)
        new_vars, _ = _states_list(new_vars)
        vars_ = [_to_nd(v) for v in new_vars]
        if o is not None:
            o_list, _ = _states_list(o)
            outs.append(o_list)
        steps += 1
    if outs:
        stacked = []
        for k in range(len(outs[0])):
            rows = [o[k] for o in outs]
            # pad to max_iterations like the reference's static output
            pad_rows = [zeros(rows[0].shape, dtype=rows[0].dtype)
                        for _ in range(max_iterations - len(rows))]
            stacked.append(nd_stack(*(rows + pad_rows), axis=0))
        out = stacked[0] if len(stacked) == 1 else stacked
    else:
        out = []
    return out, (vars_[0] if single else vars_)


def cond(pred, then_func, else_func):
    """Reference control_flow.cc _cond.

    Eagerly evaluates the predicate and runs one branch (imperative
    semantics: the tape records only the taken branch, like the
    reference); traced values route through lax.cond.
    """
    from .. import autograd
    from ..ndarray.ndarray import NDArray

    p = pred() if callable(pred) else pred
    p_val = p._data if isinstance(p, NDArray) else jnp.asarray(p)
    if autograd.is_recording() or not isinstance(
            p_val, jax.core.Tracer):
        take_then = bool(jnp.asarray(p_val).reshape(()))
        return then_func() if take_then else else_func()

    def wrap(branch):
        def f(_):
            out = branch()
            o_list, single = _states_list(out)
            return tuple(_data(o) for o in o_list)

        return f

    outs = lax.cond(p_val.reshape(()).astype(bool), wrap(then_func),
                    wrap(else_func), operand=None)
    outs = [NDArray(o) for o in outs]
    return outs[0] if len(outs) == 1 else outs
