"""Control-flow operators: foreach / while_loop / cond.

Reference parity: src/operator/control_flow.cc:1089-1255 (_foreach,
_while_loop, _cond higher-order ops executing subgraph Symbols) and the
python surface mx.nd.contrib.foreach/while_loop/cond
(python/mxnet/ndarray/contrib.py).

TPU-native design: under jit tracing the bodies lower to lax.scan /
lax.while_loop / lax.cond — compiler-friendly control flow with no
Python in the loop.  Under eager autograd recording, the loop runs as a
taped Python loop instead (lax.while_loop is not reverse-mode
differentiable; the unrolled tape is, exactly like the reference's
imperative path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError


def _to_nd(x):
    from ..ndarray.ndarray import NDArray

    return x if isinstance(x, NDArray) else NDArray(jnp.asarray(x))


def _data(x):
    from ..ndarray.ndarray import NDArray

    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _states_list(states):
    single = not isinstance(states, (list, tuple))
    return ([states] if single else list(states)), single


def foreach(body, data, init_states):
    """Scan ``body(data_slice, states) -> (out, new_states)`` over axis 0
    of ``data`` (reference control_flow.cc _foreach).

    Eager+recording: taped Python loop.  Otherwise: lax.scan (one
    compiled loop, O(1) program size in sequence length).
    """
    from .. import autograd
    from ..ndarray.ndarray import NDArray

    datas, data_single = _states_list(data)
    states, states_single = _states_list(init_states)
    n = datas[0].shape[0]

    if autograd.is_recording():
        outs = []
        cur = [_to_nd(s) for s in states]
        for i in range(n):
            sl = [d[i] for d in datas]
            o, cur = body(sl[0] if data_single else sl,
                          cur[0] if states_single else cur)
            cur, _ = _states_list(cur)
            outs.append(o)
        from ..ndarray.ndarray import stack as nd_stack

        if isinstance(outs[0], (list, tuple)):
            stacked = [nd_stack(*[o[k] for o in outs], axis=0)
                       for k in range(len(outs[0]))]
        else:
            stacked = nd_stack(*outs, axis=0)
        return stacked, (cur[0] if states_single else cur)

    def scan_body(carry, xs):
        sl = [NDArray(x) for x in xs]
        st = [NDArray(c) for c in carry]
        o, new_st = body(sl[0] if data_single else sl,
                         st[0] if states_single else st)
        new_st, _ = _states_list(new_st)
        o_list, o_single = _states_list(o)
        return (tuple(_data(s) for s in new_st),
                tuple(_data(x) for x in o_list))

    carry, ys = lax.scan(scan_body, tuple(_data(s) for s in states),
                         tuple(_data(d) for d in datas))
    outs = [NDArray(y) for y in ys]
    states_out = [NDArray(c) for c in carry]
    out = outs[0] if len(outs) == 1 else outs
    return out, (states_out[0] if states_single else states_out)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Reference control_flow.cc _while_loop: run ``func`` while ``cond``
    holds, stacking per-step outputs padded to ``max_iterations``.

    Returns (outputs, final_loop_vars).  Python loop (the reference's
    imperative semantics — step outputs make the trip count data-
    dependent, which XLA cannot express with stacked outputs; loops
    without outputs should use lax.while_loop directly).
    """
    from ..ndarray.ndarray import NDArray, stack as nd_stack, zeros

    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    vars_, single = _states_list(loop_vars)
    vars_ = [_to_nd(v) for v in vars_]
    outs = []
    steps = 0
    while steps < max_iterations:
        c = cond(vars_[0] if single else vars_)
        c_val = bool(c.asnumpy().reshape(()) if isinstance(c, NDArray)
                     else c)
        if not c_val:
            break
        o, new_vars = func(vars_[0] if single else vars_)
        new_vars, _ = _states_list(new_vars)
        vars_ = [_to_nd(v) for v in new_vars]
        if o is not None:
            o_list, _ = _states_list(o)
            outs.append(o_list)
        steps += 1
    if outs:
        stacked = []
        for k in range(len(outs[0])):
            rows = [o[k] for o in outs]
            # pad to max_iterations like the reference's static output
            pad_rows = [zeros(rows[0].shape, dtype=rows[0].dtype)
                        for _ in range(max_iterations - len(rows))]
            stacked.append(nd_stack(*(rows + pad_rows), axis=0))
        out = stacked[0] if len(stacked) == 1 else stacked
    else:
        out = []
    return out, (vars_[0] if single else vars_)


def cond(pred, then_func, else_func):
    """Reference control_flow.cc _cond.

    Eagerly evaluates the predicate and runs one branch (imperative
    semantics: the tape records only the taken branch, like the
    reference); traced values route through lax.cond.
    """
    from .. import autograd
    from ..ndarray.ndarray import NDArray

    p = pred() if callable(pred) else pred
    p_val = p._data if isinstance(p, NDArray) else jnp.asarray(p)
    if autograd.is_recording() or not isinstance(
            p_val, jax.core.Tracer):
        take_then = bool(jnp.asarray(p_val).reshape(()))
        return then_func() if take_then else else_func()

    def wrap(branch):
        def f(_):
            out = branch()
            o_list, single = _states_list(out)
            return tuple(_data(o) for o in o_list)

        return f

    outs = lax.cond(p_val.reshape(()).astype(bool), wrap(then_func),
                    wrap(else_func), operand=None)
    outs = [NDArray(o) for o in outs]
    return outs[0] if len(outs) == 1 else outs


# ------------------------------------------------------------------
# Graph-level control flow: registered ops executing subgraph Symbols
# (reference src/operator/control_flow.cc:1089-1255 — _foreach,
# _while_loop, _cond as nnvm ops whose subgraphs serialize with the
# graph).  Subgraphs travel as JSON-text attrs so Symbol.tojson()/load
# round-trips them; evaluation lowers onto lax.scan / masked scan /
# lax.cond inside the executor's single XLA program.
# ------------------------------------------------------------------
import json as _json

from .registry import register_op as _register_op

_SUBGRAPH_CACHE = {}


def _load_subgraph(sg):
    """Attr value -> Symbol; accepts the JSON text (or the dict a JSON
    round-trip may literal-eval it into)."""
    if not isinstance(sg, str):
        sg = _json.dumps(sg)
    sym = _SUBGRAPH_CACHE.get(sg)
    if sym is None:
        from ..symbol import load_json

        sym = load_json(sg)
        _SUBGRAPH_CACHE[sg] = sym
    return sym


def _names(v):
    if isinstance(v, str):
        return _json.loads(v)
    return list(v)


def _check_no_aux_mutation(sub, train, opname):
    """BatchNorm moving-stat updates inside a subgraph cannot be
    threaded out through a fixed-arity graph op; fail loudly instead of
    training with silently stale statistics (reference shares aux
    arrays imperatively, control_flow.cc)."""
    if not train:
        return
    for node in sub._topo():
        if node.op in ("BatchNorm", "BatchNorm_v1", "SyncBatchNorm") \
                and not node.attrs.get("use_global_stats", False):
            raise MXNetError(
                f"{opname}: training a BatchNorm inside a control-flow "
                "subgraph would not update its moving statistics; set "
                "use_global_stats=True or move the BatchNorm outside "
                "the loop")


@_register_op("_foreach",
              num_outputs=lambda p: int(p["num_out_data"])
              + int(p["num_states"]),
              key_param="key", train_param="train")
def _foreach_graph_op(*inputs, subgraph, input_names, num_data,
                      num_states, num_out_data, key=None, train=False):
    """Scan the subgraph over axis 0 of the data inputs.

    Input slots: [data x num_data, states x num_states, remain...];
    subgraph outputs: [out_data x num_out_data, new_states].
    Reference: control_flow.cc ForeachComputeExCPU."""
    from ..symbol.executor import _eval_graph

    sub = _load_subgraph(subgraph)
    names = _names(input_names)
    nd_, ns = int(num_data), int(num_states)
    nod = int(num_out_data)
    data = inputs[:nd_]
    states = inputs[nd_:nd_ + ns]
    remain = inputs[nd_ + ns:]
    data_names = names[:nd_]
    state_names = names[nd_:nd_ + ns]
    remain_names = names[nd_ + ns:]

    _check_no_aux_mutation(sub, train, "_foreach")
    n_steps = data[0].shape[0] if data else 0

    def body(carry, xs):
        i, xs = xs[0], xs[1:]
        k = jax.random.fold_in(key, i) if key is not None else None
        env = dict(zip(remain_names, remain))
        env.update(zip(data_names, xs))
        env.update(zip(state_names, carry))
        outs, _ = _eval_graph(sub, env, k, train)
        return tuple(outs[nod:]), tuple(outs[:nod])

    carry, ys = lax.scan(body, tuple(states),
                         (jnp.arange(n_steps),) + tuple(data))
    result = list(ys) + list(carry)
    return tuple(result) if len(result) > 1 else result[0]


@_register_op("_while_loop",
              num_outputs=lambda p: int(p["num_out_data"])
              + int(p["num_states"]),
              key_param="key", train_param="train")
def _while_loop_graph_op(*inputs, cond_graph, body_graph, input_names,
                         num_states, num_out_data, max_iterations,
                         key=None, train=False):
    """Masked fixed-length scan: runs ``max_iterations`` steps, freezes
    state and zero-pads outputs once the cond subgraph goes false —
    fixed shapes, so XLA compiles one loop and the whole op stays
    reverse-mode differentiable.  Reference: control_flow.cc
    WhileLoopComputeExCPU (padded outputs, same contract)."""
    from ..symbol.executor import _eval_graph

    csub = _load_subgraph(cond_graph)
    bsub = _load_subgraph(body_graph)
    names = _names(input_names)
    ns, nod = int(num_states), int(num_out_data)
    states = inputs[:ns]
    remain = inputs[ns:]
    state_names = names[:ns]
    remain_names = names[ns:]

    _check_no_aux_mutation(csub, train, "_while_loop")
    _check_no_aux_mutation(bsub, train, "_while_loop")

    def step(carry, i):
        active, st = carry[0], carry[1:]
        k = jax.random.fold_in(key, i) if key is not None else None
        env = dict(zip(remain_names, remain))
        env.update(zip(state_names, st))
        c_out, _ = _eval_graph(csub, env, k, train)
        pred = jnp.logical_and(active,
                               c_out[0].reshape(()).astype(bool))
        b_outs, _ = _eval_graph(bsub, env, k, train)
        out_d = b_outs[:nod]
        new_st = b_outs[nod:]
        st2 = tuple(jnp.where(pred, n, o) for n, o in zip(new_st, st))
        od = tuple(jnp.where(pred, o, jnp.zeros_like(o)) for o in out_d)
        return (pred,) + st2, od

    carry, ys = lax.scan(step, (jnp.bool_(True),) + tuple(states),
                         jnp.arange(int(max_iterations)))
    result = list(ys) + list(carry[1:])
    return tuple(result) if len(result) > 1 else result[0]


@_register_op("_cond", num_outputs=lambda p: int(p["num_outputs"]),
              key_param="key", train_param="train")
def _cond_graph_op(*inputs, cond_graph, then_graph, else_graph,
                   input_names, num_outputs, key=None, train=False):
    """lax.cond over then/else subgraphs; the pred subgraph sees the
    same inputs.  Reference: control_flow.cc CondComputeExCPU."""
    from ..symbol.executor import _eval_graph

    psub = _load_subgraph(cond_graph)
    tsub = _load_subgraph(then_graph)
    esub = _load_subgraph(else_graph)
    for sub_ in (psub, tsub, esub):
        _check_no_aux_mutation(sub_, train, "_cond")
    names = _names(input_names)
    env = dict(zip(names, inputs))
    p_out, _ = _eval_graph(psub, env, key, train)
    pred = p_out[0].reshape(()).astype(bool)

    def _branch(sub):
        def f(ins):
            outs, _ = _eval_graph(sub, dict(zip(names, ins)), key, train)
            return tuple(outs)
        return f

    outs = lax.cond(pred, _branch(tsub), _branch(esub), tuple(inputs))
    return tuple(outs) if len(outs) > 1 else outs[0]
