"""Optimizer update operators — optimizers as ops.

Reference parity: src/operator/optimizer_op.cc (sgd_update,
sgd_mom_update, adam_update, rmsprop_update, ftrl_update, signsgd/
signum, nag_mom_update, + the multi-tensor variants used by
DataParallel training and Horovod).  Each op delegates to the SAME
jitted rule functions the Optimizer classes use, so all three surfaces
(Optimizer.update, fused_update, these ops) share one implementation.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..optimizer.optimizer import (_adagrad_step, _adam_step,
                                   _ftrl_step, _lars_bucket_step,
                                   _nag_step, _rmsprop_alex_step,
                                   _rmsprop_step, _sgd_mom_step,
                                   _sgd_step, _signum_step)
from .registry import register_op


def _prep(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register_op("sgd_update", differentiable=False)
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    """Reference: optimizer_op.cc sgd_update."""
    g = _prep(grad, rescale_grad, clip_gradient)
    return _sgd_step(weight, g, lr, wd)


@register_op("sgd_mom_update", num_outputs=2, differentiable=False)
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0,
                   lazy_update=True):
    """Reference: optimizer_op.cc sgd_mom_update.  Returns (weight,
    mom) — functional outputs instead of the reference's in-place
    mutation (XLA has no aliasing op outputs at this surface)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    return _sgd_mom_step(weight, mom, g, lr, wd, momentum)


@register_op("nag_mom_update", num_outputs=2, differentiable=False)
def nag_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return _nag_step(weight, mom, g, lr, wd, momentum)


@register_op("adam_update", num_outputs=3, differentiable=False)
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, t=1.0, lazy_update=True):
    """Reference: optimizer_op.cc adam_update (+ explicit t for the
    bias correction the reference tracks per-weight internally)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    return _adam_step(weight, mean, var, g, lr, wd, beta1, beta2,
                      epsilon, t)


@register_op("rmsprop_update", num_outputs=2, differentiable=False)
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_w, new_n = _rmsprop_step(weight, n, g, lr, wd, gamma1, epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register_op("rmspropalex_update", num_outputs=4, differentiable=False)
def rmspropalex_update(weight, grad, n, g_avg, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return _rmsprop_alex_step(weight, n, g_avg, delta, g, lr, wd,
                              gamma1, gamma2, epsilon)


@register_op("ftrl_update", num_outputs=3, differentiable=False)
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0,
                wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return _ftrl_step(weight, z, n, g, lr, wd, lamda1, beta)


@register_op("signsgd_update", differentiable=False)
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return (1 - lr * wd) * weight - lr * jnp.sign(g)


@register_op("signum_update", num_outputs=2, differentiable=False)
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return _signum_step(weight, mom, g, lr, wd, momentum, wd_lh)


@register_op("adagrad_update", num_outputs=2, differentiable=False,
             aliases=("_sparse_adagrad_update",))
def adagrad_update(weight, grad, history, *, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return _adagrad_step(weight, history, g, lr, wd, epsilon)


# ------------------------------------------------- multi-tensor variants
@register_op("multi_sgd_update",
             num_outputs=lambda p: p.get("num_weights", 1),
             differentiable=False)
def multi_sgd_update(*args, lrs, wds, num_weights=1, rescale_grad=1.0,
                     clip_gradient=-1.0):
    """Reference: optimizer_op.cc multi_sgd_update (one fused launch for
    many small tensors — XLA fuses these anyway; kept for API parity)."""
    weights = args[:num_weights]
    grads = args[num_weights:2 * num_weights]
    outs = []
    for w, g, lr, wd in zip(weights, grads, lrs, wds):
        outs.append(_sgd_step(w, _prep(g, rescale_grad, clip_gradient),
                              lr, wd))
    return tuple(outs)


@register_op("multi_sgd_mom_update",
             num_outputs=lambda p: 2 * p.get("num_weights", 1),
             differentiable=False)
def multi_sgd_mom_update(*args, lrs, wds, momentum=0.0, num_weights=1,
                         rescale_grad=1.0, clip_gradient=-1.0):
    weights = args[:num_weights]
    grads = args[num_weights:2 * num_weights]
    moms = args[2 * num_weights:3 * num_weights]
    new_w, new_m = [], []
    for w, g, m, lr, wd in zip(weights, grads, moms, lrs, wds):
        nw, nm = _sgd_mom_step(w, m, _prep(g, rescale_grad,
                                           clip_gradient), lr, wd,
                               momentum)
        new_w.append(nw)
        new_m.append(nm)
    return tuple(new_w) + tuple(new_m)


# ------------------------------------- bucketed flat-tensor variants
# (round 9): ONE launch over a dtype-homogeneous FLAT bucket holding
# many parameters — the sharded-server exchange's inner update
# (parallel.zero / make_train_step optimizer_sharding="ps") exposed as
# standalone ops, the multi_mp_sgd/multi_lars analog: where the
# reference fuses N small tensors into one kernel by looping inside
# it, the flat layout IS the fusion.
@register_op("_fused_bucket_sgd_mom_update", num_outputs=2,
             differentiable=False)
def fused_bucket_sgd_mom_update(weight, grad, mom, *, lr, momentum=0.9,
                                wd=0.0, rescale_grad=1.0,
                                clip_gradient=-1.0):
    """SGD+momentum over one flat bucket (reference analog:
    multi_sgd_mom_update / multi_mp_sgd_mom_update)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    return _sgd_mom_step(weight, mom, g, lr, wd, momentum)


@register_op("_fused_bucket_adam_update", num_outputs=3,
             differentiable=False)
def fused_bucket_adam_update(weight, grad, mean, var, *, lr, beta1=0.9,
                             beta2=0.999, epsilon=1e-8, wd=0.0,
                             rescale_grad=1.0, clip_gradient=-1.0,
                             t=1.0):
    """Adam over one flat bucket (both moment slots ride the same flat
    layout — the per-chip state the ZeRO-1 shard owns)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    return _adam_step(weight, mean, var, g, lr, wd, beta1, beta2,
                      epsilon, t)


@register_op("_fused_bucket_lars_update", num_outputs=2,
             differentiable=False)
def fused_bucket_lars_update(weight, grad, mom, seg_ids, *, lr,
                             num_segments, momentum=0.9, lars_eta=0.001,
                             lars_epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0):
    """LARS over one flat bucket: per-parameter trust ratios recovered
    from segment-summed norms (``seg_ids`` maps elements to their
    parameter — the multi_sum_sq + multi_lars pipeline in one op)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    return _lars_bucket_step(weight, mom, g,
                             seg_ids.astype(jnp.int32), lr, wd,
                             momentum, lars_eta, lars_epsilon,
                             int(num_segments))


@register_op("multi_sum_sq",
             num_outputs=1, differentiable=False)
def multi_sum_sq(*arrays, num_arrays=1):
    """Reference: contrib/multi_sum_sq.cc (LARS norm helper)."""
    return jnp.stack([jnp.sum(a.astype(jnp.float32) ** 2)
                      for a in arrays])


@register_op("multi_lars", differentiable=False)
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, *, eta, eps,
               rescale_grad=1.0):
    """Reference: contrib/multi_lars.cc — layer-wise LR scaling from
    precomputed squared norms."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    trust = jnp.where((w_norm > 0) & (g_norm > 0),
                      eta * w_norm / (g_norm + wds * w_norm + eps), 1.0)
    return lrs * trust
