"""Operator library package: importing this registers every op.

The registry (ops/registry.py) replaces NNVM op registration; each submodule
documents which reference source tree it covers (SURVEY.md §2.3).
"""
from . import (  # noqa: F401  (import-for-registration)
    elemwise,
    reduce,
    shape_ops,
    nn,
    conv,
    rnn,
    random_ops,
    sort_ops,
    sequence_ops,
    linalg_ops,
    contrib_ops,
    contrib_tail,
    numpy_ops,
    detection_ops,
    flash_attention,
    quantization_ops,
    control_flow_ops,
    optimizer_ops,
    collective_ops,
    pallas_conv,
    pallas_opt,
)
from .registry import OpDef, alias_op, get_op, list_ops, register_op  # noqa: F401
