"""Pallas TPU fused-bucket optimizer kernels (round 14).

The round-9 ``_fused_bucket_{sgd_mom,adam,lars}_update`` ops timed the
sharded-server exchange's inner update as *jnp* over one flat bucket —
XLA already fuses the elementwise math, but each optimizer slot still
round-trips HBM separately and the dynamic-loss-scale finiteness check
is a second full pass over the gradient.  These kernels run the whole
per-shard update — gradient prep (rescale/clip), the optimizer rule,
and the loss-scale ``isfinite(g).all()`` verdict — in ONE streamed
VMEM pass over (w, g, state): every operand is read from HBM exactly
once (reference analog: the multi-tensor fused optimizer launches,
src/operator/optimizer_op.cc + contrib/multi_lars.cc).

They are *autotune variants*, not defaults: ``parallel.zero.
bucket_shard_update`` consults the ``fused_bucket_opt`` variant op
(``autotune.VARIANT_OPS``), so the kernel races the jnp baseline
INSIDE the caller's real jitted step (the r05 lesson: isolation wins
can be in-step losses) and is adopted per (shape, dtype, platform,
mesh) only where it wins.  Off-TPU the kernels run in interpret mode
— numerically identical, so the tier-1 parity tests and the CPU bench
smoke exercise the exact kernel code path.

Math parity contract (tests/test_pallas_opt.py): bit-exact vs the jnp
``fused_bucket_update`` for fp32 sgd/sgd_mom/adam (same expressions in
the same evaluation order), allclose for LARS (the segment-sum
reduction order differs between ``jax.ops.segment_sum`` and the
kernel's per-segment masked sums).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas imports only where available (CPU wheels carry it too)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

#: LARS buckets with more parameters than this fall back to jnp (the
#: per-segment reductions unroll inside the kernel)
_MAX_SEGMENTS = 128

_LANE = 128


def _on_tpu():
    from .pallas_conv import _on_tpu as _probe  # the shared backend
    #                                             probe (one copy)

    return _probe()


def _default_interpret():
    """Interpret mode off-TPU: same kernel code, reference semantics —
    slow, so it only ever runs when a test forces the variant or a
    CPU race measures it (where it loses to jnp, correctly)."""
    return not _on_tpu()


def _view2d(flat):
    """TPU-friendly 2-D view of a flat bucket shard: zero-pad to a
    lane multiple and reshape (rows, 128), so block streaming (and the
    VMEM budget math in _block_rows) holds for EVERY shard length —
    shard lengths are ceil(bucket/n_shards), almost never
    lane-divisible, and a single unblocked (1, L) tile would blow the
    16MB budget on any large bucket.  Zero padding is safe everywhere:
    the kernels are elementwise (pad lanes are computed then sliced
    off), zeros are finite (no phantom non-finite counts), and zero
    w/g contribute nothing to the LARS norms."""
    n = int(flat.shape[0])
    pad = (-n) % _LANE
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape((n + pad) // _LANE, _LANE)


def _block_rows(rows, n_operands):
    """Largest row-block whose double-buffered VMEM plan stays well
    inside the 16MB/core budget."""
    budget = 12 * 1024 * 1024
    per_row = 2 * n_operands * _LANE * 4  # double-buffered f32 blocks
    bm = max(budget // per_row, 8)
    for cand in (4096, 2048, 1024, 512, 256, 64, 8):
        if cand <= bm:
            return min(cand, rows) if rows >= 8 else rows
    return rows


def _grid_plan(v2d, n_operands):
    rows = v2d.shape[0]
    bm = _block_rows(rows, n_operands)
    nb = -(-rows // bm)
    return bm, nb


def _live_mask(i, bm, rows, width):
    """Rows of this block that exist in the array (the last block may
    run past ``rows``; out-of-bounds reads hold unspecified bits that
    must not reach the finiteness count)."""
    r = jax.lax.broadcasted_iota(jnp.int32, (bm, width), 0) + i * bm
    return r < rows


def _nf_count(g, live):
    """Non-finite count of the RAW (pre-cast) gradient block — the
    dynamic-loss-scale check fused onto the same VMEM read."""
    bad = jnp.logical_and(jnp.logical_not(jnp.isfinite(
        g.astype(jnp.float32))), live)
    return jnp.sum(bad.astype(jnp.float32))


def _prep_block(g, rescale, clip):
    """Optimizer._prep, verbatim: g*rescale then symmetric clip."""
    g = g * rescale
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    return g


# ------------------------------------------------------------ sgd kernels
def _nf_accumulate(i, graw, live, nf_ref, acc_ref):
    """Fold this block's non-finite count into the grid-carried
    accumulator; write the total at the last step.  Called only when
    the caller asked for the fused verdict — a with_finite=False build
    compiles none of this (nf_ref/acc_ref are absent)."""
    part = _nf_count(graw, live)

    @pl.when(i == 0)
    def _():
        acc_ref[0, 0] = part

    @pl.when(i > 0)
    def _():
        acc_ref[0, 0] = acc_ref[0, 0] + part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        nf_ref[0, 0] = acc_ref[0, 0]


def _sgd_kernel(w_ref, g_ref, ow_ref, nf_ref=None, acc_ref=None, *,
                lr, wd, rescale, clip, momentum, rows, bm):
    i = pl.program_id(0)
    graw = g_ref[:]
    w = w_ref[:]
    g = _prep_block(graw.astype(w.dtype), rescale, clip)
    ow_ref[:] = w - lr * (g + wd * w)
    if nf_ref is not None:
        _nf_accumulate(i, graw, _live_mask(i, bm, rows, w.shape[1]),
                       nf_ref, acc_ref)


def _sgd_mom_kernel(w_ref, g_ref, m_ref, ow_ref, om_ref, nf_ref=None,
                    acc_ref=None, *, lr, wd, momentum, rescale, clip,
                    rows, bm):
    i = pl.program_id(0)
    graw = g_ref[:]
    w = w_ref[:]
    g = _prep_block(graw.astype(w.dtype), rescale, clip)
    # _sgd_mom_step, verbatim order
    mom = momentum * m_ref[:] - lr * (g + wd * w)
    ow_ref[:] = w + mom
    om_ref[:] = mom
    if nf_ref is not None:
        _nf_accumulate(i, graw, _live_mask(i, bm, rows, w.shape[1]),
                       nf_ref, acc_ref)


def _adam_kernel(lrt_ref, w_ref, g_ref, m_ref, v_ref, ow_ref, om_ref,
                 ov_ref, nf_ref=None, acc_ref=None, *, wd, beta1,
                 beta2, eps, rescale, clip, rows, bm):
    i = pl.program_id(0)
    graw = g_ref[:]
    w = w_ref[:]
    lr_t = lrt_ref[0]
    # Adam.fused_update -> _adam_step, verbatim order
    g = _prep_block(graw.astype(w.dtype), rescale, clip)
    g = g + wd * w
    m = beta1 * m_ref[:] + (1 - beta1) * g
    v = beta2 * v_ref[:] + (1 - beta2) * g * g
    ow_ref[:] = w - lr_t * m / (jnp.sqrt(v) + eps)
    om_ref[:] = m
    ov_ref[:] = v
    if nf_ref is not None:
        _nf_accumulate(i, graw, _live_mask(i, bm, rows, w.shape[1]),
                       nf_ref, acc_ref)


# ------------------------------------------------------------ lars kernels
def _lars_norms_kernel(w_ref, g_ref, seg_ref, wss_ref, gss_ref,
                       accw_ref, accg_ref, *, nseg, segp, rescale,
                       clip, rows, bm):
    """Phase A: per-parameter squared norms of (w, prepped g) from the
    flat layout — the multi_sum_sq half of the LARS pipeline, fused
    onto the same block read the update will repeat."""
    i = pl.program_id(0)
    w = w_ref[:].astype(jnp.float32)
    g = _prep_block(g_ref[:].astype(jnp.float32), rescale, clip)
    seg = seg_ref[:]
    live = _live_mask(i, bm, rows, w.shape[1])
    wsq = jnp.where(live, w * w, 0.0)
    gsq = jnp.where(live, g * g, 0.0)
    w_parts = [jnp.sum(jnp.where(seg == s, wsq, 0.0))
               for s in range(nseg)]
    g_parts = [jnp.sum(jnp.where(seg == s, gsq, 0.0))
               for s in range(nseg)]
    pad = [jnp.float32(0.0)] * (segp - nseg)
    w_row = jnp.stack(w_parts + pad).reshape(1, segp)
    g_row = jnp.stack(g_parts + pad).reshape(1, segp)

    @pl.when(i == 0)
    def _():
        accw_ref[:] = w_row
        accg_ref[:] = g_row

    @pl.when(i > 0)
    def _():
        accw_ref[:] = accw_ref[:] + w_row
        accg_ref[:] = accg_ref[:] + g_row

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        wss_ref[:] = accw_ref[:]
        gss_ref[:] = accg_ref[:]


def _lars_update_kernel(w_ref, g_ref, m_ref, seg_ref, slr_ref, ow_ref,
                        om_ref, *, nseg, wd, momentum, rescale, clip):
    """Phase B: the momentum update with the per-parameter scaled lr
    broadcast back over the flat layout (multi_lars + the update)."""
    w = w_ref[:].astype(jnp.float32)
    g = _prep_block(g_ref[:].astype(jnp.float32), rescale, clip)
    seg = seg_ref[:]
    svec = slr_ref[:]  # (1, segp)
    slr = jnp.zeros_like(w)
    for s in range(nseg):
        slr = jnp.where(seg == s, svec[0, s], slr)
    # _lars_bucket_step, verbatim order
    mom = momentum * m_ref[:].astype(jnp.float32) + slr * (g + wd * w)
    ow_ref[:] = (w - mom).astype(ow_ref.dtype)
    om_ref[:] = mom.astype(om_ref.dtype)


# -------------------------------------------------------------- dispatch
def _elementwise_call(kernel, n_in, n_out, operands, out_dtypes,
                      scalars=(), interpret=False, with_finite=False):
    """Run an elementwise bucket kernel over the lane-padded 2-D view.
    ``operands`` are flat 1-D arrays of one length; ``scalars`` ride
    SMEM.  ``with_finite`` adds the fused (1,1) non-finite-count
    output (+ its scratch accumulator); False compiles the check out
    entirely, matching the jnp arm's zero cost."""
    v2ds = [_view2d(a) for a in operands]
    rows, width = v2ds[0].shape
    bm, nb = _grid_plan(v2ds[0], n_in + n_out)
    blk = pl.BlockSpec((bm, width), lambda i: (i, 0))
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)
                for _ in scalars] + [blk] * len(operands)
    out_specs = [blk] * len(out_dtypes)
    out_shape = [jax.ShapeDtypeStruct((rows, width), dt)
                 for dt in out_dtypes]
    scratch = []
    if with_finite:
        out_specs = out_specs + [pl.BlockSpec((1, 1), lambda i: (0, 0))]
        out_shape = out_shape + [jax.ShapeDtypeStruct((1, 1),
                                                      jnp.float32)]
        scratch = [pltpu.VMEM((1, 1), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(kernel, rows=rows, bm=bm),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*scalars, *v2ds)
    n = operands[0].shape[0]
    nf = None
    if with_finite:
        nf = outs[-1][0, 0]
        outs = outs[:-1]
    flat_outs = [o.reshape(-1)[:n] for o in outs]
    return flat_outs, nf


def supported(opt, dtype, nseg=None):
    """None when these kernels can run this optimizer on a bucket of
    ``dtype``; otherwise a human-readable reason (the caller falls back
    to the jnp rule and, in a race, the jnp arm simply wins)."""
    import numpy as onp

    from ..optimizer.optimizer import LARS, SGD, Adam

    if not _HAVE_PALLAS:
        return "pallas unavailable"
    dt = onp.dtype(dtype)
    if type(opt) is SGD:
        if dt not in (onp.dtype(onp.float32), onp.dtype(jnp.bfloat16)):
            return f"sgd kernel supports f32/bf16 buckets, not {dt}"
        return None
    if type(opt) is Adam:
        if dt != onp.dtype(onp.float32):
            return f"adam kernel supports f32 buckets, not {dt}"
        return None
    if type(opt) is LARS:
        if dt != onp.dtype(onp.float32):
            return f"lars kernel supports f32 buckets, not {dt}"
        if nseg is not None and nseg > _MAX_SEGMENTS:
            return f"lars bucket has {nseg} segments (> {_MAX_SEGMENTS})"
        return None
    return f"no pallas bucket kernel for {type(opt).__name__}"


def bucket_update(opt, w, g, state, t, *, seg=None, axis_name=None,
                  interpret=None, with_finite=False):
    """One fused VMEM pass over a flat bucket shard: gradient prep +
    optimizer rule + (optionally) the loss-scale finiteness verdict of
    the RAW gradient.  Mirrors ``opt.fused_bucket_update`` (same
    inputs, same update math); returns ``(new_w, new_state, finite)``
    with ``finite=None`` unless ``with_finite``.  Returns ``None``
    when :func:`supported` says the kernels cannot run this bucket —
    the caller keeps the jnp rule."""
    from ..optimizer.optimizer import LARS, SGD, Adam

    nseg = None
    if seg is not None:
        nseg = int(seg[1])
    if supported(opt, w.dtype, nseg=nseg) is not None:
        return None
    if interpret is None:
        interpret = _default_interpret()
    rescale = float(opt.rescale_grad)
    clip = None if opt.clip_gradient is None else \
        float(opt.clip_gradient)

    if type(opt) is SGD:
        lr, wd, momentum = (float(opt.learning_rate), float(opt.wd),
                            float(opt.momentum))
        if momentum == 0.0:
            (new_w,), nf = _elementwise_call(
                functools.partial(_sgd_kernel, lr=lr, wd=wd,
                                  momentum=momentum, rescale=rescale,
                                  clip=clip),
                n_in=2, n_out=1, operands=[w, g],
                out_dtypes=[w.dtype], interpret=interpret,
                with_finite=with_finite)
            # momentum zeroed live: pass any state slot through
            # untouched, like SGD.fused_update
            new_state = state
        else:
            (mom,) = state
            (new_w, new_m), nf = _elementwise_call(
                functools.partial(_sgd_mom_kernel, lr=lr, wd=wd,
                                  momentum=momentum, rescale=rescale,
                                  clip=clip),
                n_in=3, n_out=2, operands=[w, g, mom],
                out_dtypes=[w.dtype, mom.dtype], interpret=interpret,
                with_finite=with_finite)
            new_state = (new_m,)
    elif type(opt) is Adam:
        m, v = state
        # the bias-corrected lr is a 3-op scalar: computed OUTSIDE the
        # kernel with the exact _adam_step expression, streamed in via
        # SMEM (t is traced; everything else is static)
        coef1 = 1.0 - opt.beta1 ** t
        coef2 = 1.0 - opt.beta2 ** t
        lr_t = (opt.learning_rate * jnp.sqrt(coef2) / coef1).astype(
            jnp.float32).reshape(1)
        (new_w, new_m, new_v), nf = _elementwise_call(
            functools.partial(_adam_kernel, wd=float(opt.wd),
                              beta1=float(opt.beta1),
                              beta2=float(opt.beta2),
                              eps=float(opt.epsilon), rescale=rescale,
                              clip=clip),
            n_in=4, n_out=3, operands=[w, g, m, v],
            out_dtypes=[w.dtype, m.dtype, v.dtype],
            scalars=(lr_t,), interpret=interpret,
            with_finite=with_finite)
        new_state = (new_m, new_v)
    elif type(opt) is LARS:
        res = _lars_bucket(opt, w, g, state, seg, axis_name, rescale,
                           clip, interpret, with_finite)
        if res is None:  # whole-tensor bucket: no kernel form
            return None
        new_w, new_state, nf = res
    else:  # pragma: no cover — supported() already filtered
        return None
    finite = (nf == 0.0) if with_finite else None
    return new_w, new_state, finite


def _lars_bucket(opt, w, g, state, seg, axis_name, rescale, clip,
                 interpret, with_finite=False):
    """Two-kernel LARS: per-segment squared norms (phase A, fused with
    the finiteness count via jnp — norms are the expensive read), the
    tiny trust-ratio vector in plain jnp (+ the cross-shard psum the
    kernel cannot host), then the elementwise update (phase B)."""
    if seg is None:
        # whole-tensor bucket: LARS.fused_bucket_update degenerates to
        # the per-param rule; no kernel form for that path
        return None
    (mom,) = state
    ids, nseg = seg
    segp = -(-int(nseg) // _LANE) * _LANE
    ids = jnp.asarray(ids, jnp.int32)
    v2w, v2g, v2s = _view2d(w), _view2d(g), _view2d(ids)
    rows, width = v2w.shape
    bm, nb = _grid_plan(v2w, 5)
    blk = pl.BlockSpec((bm, width), lambda i: (i, 0))
    vec = pl.BlockSpec((1, segp), lambda i: (0, 0))
    wss, gss = pl.pallas_call(
        functools.partial(_lars_norms_kernel, nseg=int(nseg),
                          segp=segp, rescale=rescale, clip=clip,
                          rows=rows, bm=bm),
        grid=(nb,),
        in_specs=[blk, blk, blk],
        out_specs=[vec, vec],
        out_shape=[jax.ShapeDtypeStruct((1, segp), jnp.float32),
                   jax.ShapeDtypeStruct((1, segp), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, segp), jnp.float32),
                        pltpu.VMEM((1, segp), jnp.float32)],
        interpret=interpret,
    )(v2w, v2g, v2s)
    w_ss = wss.reshape(-1)[:int(nseg)]
    g_ss = gss.reshape(-1)[:int(nseg)]
    if axis_name is not None:
        w_ss = jax.lax.psum(w_ss, axis_name)
        g_ss = jax.lax.psum(g_ss, axis_name)
    # _lars_bucket_step's trust math, on the nseg-length vectors
    w_norm = jnp.sqrt(w_ss)
    g_norm = jnp.sqrt(g_ss)
    trust = jnp.where((w_norm > 0) & (g_norm > 0),
                      opt.eta * w_norm / (g_norm + opt.wd * w_norm
                                          + opt.epsilon),
                      jnp.ones_like(w_norm))
    slr = (opt.learning_rate * trust).astype(jnp.float32)
    slr = jnp.concatenate(
        [slr, jnp.zeros((segp - int(nseg),), jnp.float32)]
    ).reshape(1, segp)
    new_w2, new_m2 = pl.pallas_call(
        functools.partial(_lars_update_kernel, nseg=int(nseg),
                          wd=float(opt.wd), momentum=float(opt.momentum),
                          rescale=rescale, clip=clip),
        grid=(nb,),
        in_specs=[blk, blk, blk, blk, vec],
        out_specs=[blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, width), w.dtype),
                   jax.ShapeDtypeStruct((rows, width), mom.dtype)],
        interpret=interpret,
    )(v2w, v2g, _view2d(mom), v2s, slr)
    n = w.shape[0]
    nf = None
    if with_finite:
        nf = jnp.sum(~jnp.isfinite(g.astype(jnp.float32))).astype(
            jnp.float32)
    return (new_w2.reshape(-1)[:n], (new_m2.reshape(-1)[:n],), nf)


# ---------------------------------------------- scale-verdict machinery
# The loss-scale bookkeeping and the fp8 delayed-scaling bookkeeping
# live SIDE BY SIDE here on purpose (round 19): both consume the same
# kind of in-graph finiteness/amax evidence the fused kernels above
# surface (``with_finite``), and both answer "what scale does the NEXT
# step use" — keeping the two verdict rules in one module is what
# stops dynamic loss scaling and fp8 tensor scaling from drifting
# apart (same backoff shape, same floor discipline).

#: largest finite value of each fp8 format (ml_dtypes): e4m3fn is the
#: forward/weight format, e5m2 the gradient format (reference: the
#: FP8 training recipe every MXU-class stack converged on)
E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def scale_bookkeeping(finite, scale, good, growth_interval=2000):
    """Dynamic-loss-scale update shared by make_train_step's replicated
    and sharded arms — ONE copy, because the two must stay
    bit-identical for the sharded-vs-replicated parity contract:
    overflow halves the scale (floor 1.0); ``growth_interval``
    consecutive finite steps double it and reset the counter
    (reference amp scaler, contrib/amp loss_scaler.py)."""
    good = jnp.where(finite, good + 1, 0)
    new_scale = jnp.where(
        finite,
        jnp.where(good >= growth_interval, scale * 2.0, scale),
        jnp.maximum(scale * 0.5, 1.0))
    good = jnp.where(good >= growth_interval, 0, good)
    return new_scale.astype(jnp.float32), good


def fp8_delayed_scale(hist, new_amax, fmax=E4M3_MAX, margin=2.0):
    """One in-graph step of the fp8 delayed-scaling recipe: roll
    ``new_amax`` (this step's observed |t|_inf) into the rolling amax
    history and derive the scale the NEXT step quantizes with —
    ``fmax / (margin * max(history))`` — so the scale always lags the
    observation by one step (no data dependency of a step on its own
    amax, no host sync).

    Overflow verdict, same shape as :func:`scale_bookkeeping`'s
    halving: a non-finite observed amax (an overflowed/poisoned cast)
    enters the history as DOUBLE the previous rolling max — the next
    scale backs off by half — instead of poisoning the history with
    inf/nan.  Returns ``(new_hist, next_scale)``, both float32."""
    hist = hist.astype(jnp.float32)
    new_amax = jnp.asarray(new_amax, jnp.float32)
    finite = jnp.isfinite(new_amax)
    prev = jnp.max(hist)
    safe = jnp.where(finite, new_amax, jnp.maximum(prev, 1.0) * 2.0)
    new_hist = jnp.concatenate([hist[1:], safe[None]])
    amax = jnp.maximum(jnp.max(new_hist), 1e-12)
    next_scale = (fmax / (margin * amax)).astype(jnp.float32)
    return new_hist, next_scale


def _fp8_qdq_cast(v, scale, fmax, f8):
    """Quantize-dequantize through an fp8 grid: the values take the
    fp8 representable set (clip to ±fmax first — an out-of-range e4m3
    cast lands on NaN, and range excursions are the delayed scale's
    job to absorb, not the matmul's), the dtype returns to the input's
    so the surrounding program is unchanged."""
    wide = v.astype(jnp.float32) * scale
    q = jnp.clip(wide, -fmax, fmax).astype(f8)
    return (q.astype(jnp.float32) / scale).astype(v.dtype)


@jax.custom_vjp
def fp8_qdq(v, scale, gscale):
    """The dtype ladder's fp8 rung primitive: forward snaps ``v`` to
    the ``float8_e4m3fn`` grid at ``scale`` (activations/weights), the
    backward snaps the incoming gradient to the ``float8_e5m2`` grid
    at ``gscale`` (the wider-exponent gradient format) — a
    straight-through estimator in both directions, so matmul/conv see
    exactly fp8-valued operands while norms/softmax/reductions around
    them stay in the wide dtype.  Scales are traced scalars read from
    ``opt_state['_fp8']`` (delayed scaling, :func:`fp8_delayed_scale`);
    neither receives a gradient."""
    return _fp8_qdq_cast(v, scale, E4M3_MAX, jnp.float8_e4m3fn)


def _fp8_qdq_fwd(v, scale, gscale):
    return fp8_qdq(v, scale, gscale), gscale


def _fp8_qdq_bwd(gscale, g):
    gv = _fp8_qdq_cast(g, gscale, E5M2_MAX, jnp.float8_e5m2)
    return gv, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)


fp8_qdq.defvjp(_fp8_qdq_fwd, _fp8_qdq_bwd)


# ----------------------------------------------------- opperf registry ops
from .registry import register_op  # noqa: E402


def _mk_opt(kind, params):
    from ..optimizer.optimizer import LARS, SGD, Adam

    if kind == "sgd_mom":
        return SGD(momentum=params.get("momentum", 0.9),
                   learning_rate=params.get("lr", 0.1),
                   wd=params.get("wd", 0.0))
    if kind == "adam":
        return Adam(learning_rate=params.get("lr", 0.001),
                    wd=params.get("wd", 0.0))
    return LARS(momentum=params.get("momentum", 0.9),
                learning_rate=params.get("lr", 0.1),
                wd=params.get("wd", 0.0))


def _op_bucket_update(op_name, opt, w, g, state, seg=None):
    """The registry ops' shared dispatch: a declined kernel raises a
    NAMED error (the repo's loud-refusal convention) instead of the
    opaque None-unpack TypeError it would otherwise become."""
    from ..base import MXNetError

    nseg = None if seg is None else int(seg[1])
    reason = supported(opt, w.dtype, nseg=nseg)
    res = None if reason else bucket_update(opt, w, g, state, 1.0,
                                            seg=seg)
    if res is None:
        raise MXNetError(
            f"{op_name}: the Pallas bucket kernel cannot run this "
            f"input ({reason or 'no kernel form for this bucket'}); "
            "use the jnp twin (_fused_bucket_*) instead")
    return res


@register_op("_pallas_bucket_sgd_mom_update", num_outputs=2,
             differentiable=False, platform_sensitive=True)
def pallas_bucket_sgd_mom_update(weight, grad, mom, *, lr, momentum=0.9,
                                 wd=0.0):
    """The Pallas-kernel arm of ``_fused_bucket_sgd_mom_update`` as a
    benchmarkable op (opperf rows diff the two arms across rounds)."""
    opt = _mk_opt("sgd_mom", dict(lr=lr, momentum=momentum, wd=wd))
    new_w, (new_m,), _ = _op_bucket_update(
        "_pallas_bucket_sgd_mom_update", opt, weight, grad, (mom,))
    return new_w, new_m


@register_op("_pallas_bucket_adam_update", num_outputs=3,
             differentiable=False, platform_sensitive=True)
def pallas_bucket_adam_update(weight, grad, mean, var, *, lr, wd=0.0):
    opt = _mk_opt("adam", dict(lr=lr, wd=wd))
    new_w, (new_m, new_v), _ = _op_bucket_update(
        "_pallas_bucket_adam_update", opt, weight, grad, (mean, var))
    return new_w, new_m, new_v


@register_op("_pallas_bucket_lars_update", num_outputs=2,
             differentiable=False, platform_sensitive=True)
def pallas_bucket_lars_update(weight, grad, mom, seg_ids, *, lr,
                              num_segments, momentum=0.9, wd=0.0):
    opt = _mk_opt("lars", dict(lr=lr, momentum=momentum, wd=wd))
    new_w, (new_m,), _ = _op_bucket_update(
        "_pallas_bucket_lars_update", opt, weight, grad, (mom,),
        seg=(seg_ids, int(num_segments)))
    return new_w, new_m
