"""Ordering ops: sort / argsort / topk.

Reference parity: src/operator/tensor/ordering_op.cc (SURVEY.md §2.3).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("sort")
def sort(x, *, axis=-1, is_ascend=True):
    r = jnp.sort(x, axis=axis)
    if not is_ascend:
        r = jnp.flip(r, axis=axis if axis is not None else 0)
    return r


@register_op("argsort", differentiable=False)
def argsort(x, *, axis=-1, is_ascend=True, dtype="float32"):
    from ..dtype import normalize_dtype

    r = jnp.argsort(x, axis=axis)
    if not is_ascend:
        r = jnp.flip(r, axis=axis if axis is not None else 0)
    return r.astype(normalize_dtype(dtype))


def _topk_nout(p):
    rt = p.get("ret_typ", "indices")
    return 2 if rt == "both" else 1


@register_op("topk", num_outputs=_topk_nout, differentiable=False)
def topk(x, *, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    """Reference: ordering_op.cc TopK; uses lax.top_k on the MXU-friendly
    last axis, transposing as needed."""
    from ..dtype import normalize_dtype

    dt = normalize_dtype(dtype)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    ax = axis % x.ndim
    xt = jnp.moveaxis(x, ax, -1)
    if is_ascend:
        vals, idxs = jax.lax.top_k(-xt, k)
        vals = -vals
    else:
        vals, idxs = jax.lax.top_k(xt, k)
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax)
    if ret_typ == "value":
        return vals
    if ret_typ == "mask":
        oh = jax.nn.one_hot(idxs, xt.shape[-1], dtype=x.dtype).sum(-2)
        return jnp.moveaxis(oh, -1, ax)
    if ret_typ == "both":
        return vals, idxs.astype(dt)
    return idxs.astype(dt)
