"""Object-detection operators: multibox family, NMS, RoI ops, proposals.

Reference parity: src/operator/contrib/multibox_prior.cc,
multibox_target.cc, multibox_detection.cc, bounding_box.cc (box_nms /
box_iou), src/operator/roi_pooling.cc, src/operator/contrib/roi_align.cc,
src/operator/contrib/proposal.cc.

TPU-native design: every op is static-shaped.  Greedy bipartite matching
and NMS — sequential scans in the reference CPU kernels — become
``lax.fori_loop``s over masks; "remove a box" is "flag it suppressed",
and dropped detections are reported with id = -1 exactly like the
reference's output convention, so downstream code is shape-stable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _corner_iou(a, b):
    """IOU of (..., 4) corner boxes vs (..., 4): broadcasted."""
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:4], b[..., 2:4])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * \
        jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register_op("_contrib_MultiBoxPrior",
             aliases=("MultiBoxPrior", "_contrib_multibox_prior"),
             differentiable=False)
def multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Reference: src/operator/contrib/multibox_prior.cc:32-70.

    Anchor layout per cell: [sizes × ratios[0]] then [sizes[0] ×
    ratios[1:]]; w carries the in_height/in_width aspect correction of
    the reference.  Output (1, H*W*A, 4) corner boxes in [0, 1] coords.
    """
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    ys = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    xs = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cy, cx = jnp.meshgrid(ys, xs, indexing="ij")  # (h, w)

    whs = []
    r0 = float(ratios[0]) ** 0.5
    for s in sizes:
        whs.append((s * h / w * r0 / 2, s / r0 / 2))
    for r in ratios[1:]:
        rs = float(r) ** 0.5
        whs.append((sizes[0] * h / w * rs / 2, sizes[0] / rs / 2))
    half_w = jnp.array([p[0] for p in whs], jnp.float32)  # (A,)
    half_h = jnp.array([p[1] for p in whs], jnp.float32)

    cx = cx[..., None]
    cy = cy[..., None]
    boxes = jnp.stack([
        cx - half_w, cy - half_h, cx + half_w, cy + half_h], axis=-1)
    boxes = boxes.reshape(1, h * w * len(whs), 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _encode_loc(anchors, gt, variances):
    """AssignLocTargets (multibox_target.cc:32-54)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gx = (gt[:, 0] + gt[:, 2]) * 0.5
    gy = (gt[:, 1] + gt[:, 3]) * 0.5
    vx, vy, vw, vh = variances
    return jnp.stack([
        (gx - ax) / jnp.maximum(aw, 1e-12) / vx,
        (gy - ay) / jnp.maximum(ah, 1e-12) / vy,
        jnp.log(jnp.maximum(gw, 1e-12) / jnp.maximum(aw, 1e-12)) / vw,
        jnp.log(jnp.maximum(gh, 1e-12) / jnp.maximum(ah, 1e-12)) / vh,
    ], axis=-1)


@register_op("_contrib_MultiBoxTarget",
             aliases=("MultiBoxTarget", "_contrib_multibox_target"),
             num_outputs=3, differentiable=False)
def multibox_target(anchor, label, cls_pred, *, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5,
                    minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Reference: src/operator/contrib/multibox_target.cc:79-280.

    anchor (1, N, 4), label (B, M, 5) rows [cls, xmin, ymin, xmax, ymax]
    with cls = -1 padding, cls_pred (B, num_classes, N).  Returns
    (loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N)).
    Matching: greedy bipartite (each gt claims its best anchor), then
    per-anchor threshold matching, then optional hard-negative mining
    ranked by background probability.
    """
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]
    m = label.shape[1]

    def one_sample(lab, cpred):
        gt_cls = lab[:, 0]
        gt_valid = gt_cls >= 0  # (M,)
        gt_boxes = lab[:, 1:5]
        ious = _corner_iou(anchors[:, None, :], gt_boxes[None, :, :])
        ious = jnp.where(gt_valid[None, :], ious, -1.0)  # (N, M)

        # stage 1: greedy bipartite — iterate M times, each time pick
        # the globally best (anchor, gt) among unmatched pairs
        def bip_body(_, state):
            a_match, iou_cache, gt_taken = state
            masked = jnp.where((a_match[:, None] < 0) &
                               (~gt_taken[None, :]), ious, -1.0)
            flat = jnp.argmax(masked)
            bi, bk = flat // m, flat % m
            ok = masked[bi, bk] > 1e-6
            a_match = a_match.at[bi].set(jnp.where(ok, bk, a_match[bi]))
            iou_cache = iou_cache.at[bi].set(
                jnp.where(ok, masked[bi, bk], iou_cache[bi]))
            gt_taken = gt_taken.at[bk].set(gt_taken[bk] | ok)
            return a_match, iou_cache, gt_taken

        a_match = jnp.full((n,), -1, jnp.int32)
        iou_cache = jnp.full((n,), -1.0, jnp.float32)
        gt_taken = jnp.zeros((m,), bool)
        a_match, iou_cache, gt_taken = lax.fori_loop(
            0, m, bip_body, (a_match, iou_cache, gt_taken))

        # stage 2: threshold matching for the rest
        best_gt = jnp.argmax(ious, axis=1).astype(jnp.int32)
        best_iou = jnp.max(ious, axis=1)
        thr_pos = (a_match < 0) & (best_iou > overlap_threshold) \
            if overlap_threshold > 0 else jnp.zeros((n,), bool)
        positive = (a_match >= 0) | thr_pos
        matched_gt = jnp.where(a_match >= 0, a_match, best_gt)
        matched_iou = jnp.where(a_match >= 0, iou_cache, best_iou)

        # stage 3: negatives
        if negative_mining_ratio > 0:
            num_pos = positive.sum()
            num_neg = jnp.minimum(
                (num_pos * negative_mining_ratio).astype(jnp.int32),
                n - num_pos)
            num_neg = jnp.maximum(num_neg,
                                  int(minimum_negative_samples))
            logits = cpred  # (num_classes, N)
            mx = jnp.max(logits, axis=0)
            bg_prob = jnp.exp(logits[0] - mx) / \
                jnp.sum(jnp.exp(logits - mx), axis=0)
            cand = (~positive) & (matched_iou < negative_mining_thresh)
            score = jnp.where(cand, bg_prob, jnp.inf)  # hardest first
            order = jnp.argsort(score)
            rank = jnp.empty_like(order).at[order].set(jnp.arange(n))
            negative = cand & (rank < num_neg)
        else:
            negative = ~positive

        cls_t = jnp.where(
            positive,
            jnp.take(gt_cls, matched_gt, mode="clip") + 1.0,
            jnp.where(negative, 0.0, float(ignore_label)))
        gt_for_anchor = jnp.take(gt_boxes, matched_gt, axis=0,
                                 mode="clip")
        loc_t = jnp.where(positive[:, None],
                          _encode_loc(anchors, gt_for_anchor, variances),
                          0.0)
        loc_m = jnp.where(positive[:, None],
                          jnp.ones((n, 4), jnp.float32), 0.0)
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one_sample)(label, cls_pred)
    return loc_t, loc_m, cls_t


def _decode_loc(anchors, pred, variances, clip):
    """multibox_detection.cc:51-70 — center-offset decoding."""
    al, at, ar, ab = (anchors[:, 0], anchors[:, 1], anchors[:, 2],
                      anchors[:, 3])
    aw, ah = ar - al, ab - at
    ax, ay = (al + ar) * 0.5, (at + ab) * 0.5
    vx, vy, vw, vh = variances
    px, py, pw, ph = pred[:, 0], pred[:, 1], pred[:, 2], pred[:, 3]
    ox = px * vx * aw + ax
    oy = py * vy * ah + ay
    ow = jnp.exp(pw * vw) * aw / 2
    oh = jnp.exp(ph * vh) * ah / 2
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _nms_scan(boxes, scores, ids, valid, nms_threshold, force_suppress,
              topk):
    """Suppression scan over score-sorted boxes: returns keep mask (in
    sorted order) and the sort order.

    With topk > 0 only the top-k sorted boxes enter the O(k^2) IOU
    matrix and the suppression loop (the reference's nms_topk
    pre-filter) — essential at SSD scale (8,732 anchors)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    k = min(topk, n) if topk > 0 else n
    b = jnp.take(boxes, order[:k], axis=0)
    s_ids = jnp.take(ids, order[:k])
    s_valid_k = jnp.take(valid, order[:k])
    ious = _corner_iou(b[:, None, :], b[None, :, :])
    same_cls = (s_ids[:, None] == s_ids[None, :]) | force_suppress

    def body(i, alive):
        sup = (ious[i] > nms_threshold) & same_cls[i] & \
            (jnp.arange(k) > i)
        keep_i = alive[i] & s_valid_k[i]
        return jnp.where(keep_i & sup, False, alive)

    alive = lax.fori_loop(0, k, body, jnp.ones((k,), bool))
    keep = jnp.zeros((n,), bool).at[:k].set(alive & s_valid_k)
    return keep, order


@register_op("_contrib_MultiBoxDetection",
             aliases=("MultiBoxDetection", "_contrib_multibox_detection"),
             differentiable=False)
def multibox_detection(cls_prob, loc_pred, anchor, *, clip=True,
                       threshold=0.01, background_id=0,
                       nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Reference: src/operator/contrib/multibox_detection.cc.

    cls_prob (B, num_classes, N) softmax probs, loc_pred (B, N*4),
    anchor (1, N, 4) -> (B, N, 6) rows [id, score, xmin, ymin, xmax,
    ymax]; suppressed/invalid rows have id = -1.
    """
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]

    def one_sample(cp, lp):
        # best non-background class per anchor
        probs = cp  # (C, N)
        mask = jnp.arange(probs.shape[0]) != background_id
        fg = jnp.where(mask[:, None], probs, -1.0)
        best_cls = jnp.argmax(fg, axis=0)
        score = jnp.max(fg, axis=0)
        valid = score > threshold
        # reference id: class index shifted down past background (bg=0)
        ids = (best_cls - 1).astype(jnp.float32)
        boxes = _decode_loc(anchors, lp.reshape(n, 4), variances, clip)
        keep, order = _nms_scan(boxes, score, best_cls, valid,
                                nms_threshold, force_suppress, nms_topk)
        s_boxes = jnp.take(boxes, order, axis=0)
        s_score = jnp.take(score, order)
        s_ids = jnp.take(ids, order)
        out = jnp.concatenate([
            jnp.where(keep, s_ids, -1.0)[:, None],
            jnp.where(keep, s_score, 0.0)[:, None],
            jnp.where(keep[:, None], s_boxes, 0.0)], axis=-1)
        return out

    return jax.vmap(one_sample)(cls_prob, loc_pred)


@register_op("_contrib_box_nms", aliases=("box_nms", "_contrib_box_non_maximum_suppression"),
             differentiable=False)
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner",
            out_format="corner"):
    """Reference: src/operator/contrib/bounding_box.cc box_nms.

    data (..., N, K): boxes at coord_start..+4, score at score_index,
    optional class at id_index.  Suppressed rows are overwritten with
    -1 (the reference convention); shape is preserved.
    """
    shape = data.shape
    flat = data.reshape(-1, shape[-2], shape[-1])

    def one(batch):
        boxes = lax.dynamic_slice_in_dim(batch, coord_start, 4, axis=1)
        if in_format == "center":
            cx, cy, w, h = (boxes[:, 0], boxes[:, 1], boxes[:, 2],
                            boxes[:, 3])
            boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                               cy + h / 2], axis=-1)
        scores = batch[:, score_index]
        ids = batch[:, id_index].astype(jnp.int32) if id_index >= 0 \
            else jnp.zeros(batch.shape[0], jnp.int32)
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid = valid & (ids != background_id)
        keep, order = _nms_scan(boxes, scores, ids, valid,
                                overlap_thresh, force_suppress
                                or id_index < 0, topk)
        sorted_rows = jnp.take(batch, order, axis=0)
        if out_format != in_format:
            sb = lax.dynamic_slice_in_dim(sorted_rows, coord_start, 4,
                                          axis=1)
            if out_format == "corner":  # center -> corner
                conv = jnp.concatenate(
                    [sb[:, :2] - sb[:, 2:4] / 2,
                     sb[:, :2] + sb[:, 2:4] / 2], axis=-1)
            else:  # corner -> center
                conv = jnp.concatenate(
                    [(sb[:, :2] + sb[:, 2:4]) / 2,
                     sb[:, 2:4] - sb[:, :2]], axis=-1)
            sorted_rows = lax.dynamic_update_slice_in_dim(
                sorted_rows, conv, coord_start, axis=1)
        # reference compacts survivors to the front, -1-fills the tail
        compact = jnp.argsort(~keep, stable=True)
        keep_c = jnp.take(keep, compact)
        rows_c = jnp.take(sorted_rows, compact, axis=0)
        return jnp.where(keep_c[:, None], rows_c, -1.0)

    return jax.vmap(one)(flat).reshape(shape)


@register_op("_contrib_box_iou", aliases=("box_iou",),
             differentiable=False)
def box_iou(lhs, rhs, *, format="corner"):  # noqa: A002
    """Reference: bounding_box.cc box_iou."""
    def to_corner(b):
        if format == "center":
            return jnp.concatenate([b[..., :2] - b[..., 2:4] / 2,
                                    b[..., :2] + b[..., 2:4] / 2],
                                   axis=-1)
        return b

    a = to_corner(lhs)
    b = to_corner(rhs)
    a_shape = a.shape[:-1]
    b_shape = b.shape[:-1]
    a2 = a.reshape((-1, 4))
    b2 = b.reshape((-1, 4))
    out = _corner_iou(a2[:, None, :], b2[None, :, :])
    return out.reshape(a_shape + b_shape)


@register_op("ROIPooling", aliases=("_contrib_ROIPooling", "roi_pooling"))
def roi_pooling(data, rois, *, pooled_size, spatial_scale):
    """Reference: src/operator/roi_pooling.cc.

    data (B, C, H, W); rois (R, 5) rows [batch_idx, x1, y1, x2, y2] in
    image coords.  Exact max-pool over quantized bins, realized as
    masked max-reductions (static shapes; a bin's pixel set is a mask,
    not a slice).
    """
    ph, pw = pooled_size
    b, c, h, w = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        img = data[bidx]  # (C, H, W)

        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        # mask (H, ph): pixel y belongs to output row i
        ystart = jnp.floor(y1 + jnp.arange(ph) * bin_h)
        yend = jnp.ceil(y1 + (jnp.arange(ph) + 1) * bin_h)
        xstart = jnp.floor(x1 + jnp.arange(pw) * bin_w)
        xend = jnp.ceil(x1 + (jnp.arange(pw) + 1) * bin_w)
        my = (ys[:, None] >= ystart[None, :]) & \
            (ys[:, None] < yend[None, :]) & \
            (ys[:, None] >= 0) & (ys[:, None] < h)
        mx = (xs[:, None] >= xstart[None, :]) & \
            (xs[:, None] < xend[None, :]) & \
            (xs[:, None] >= 0) & (xs[:, None] < w)
        neg = jnp.finfo(data.dtype).min
        masked = jnp.where(my.T[None, :, :, None], img[:, None, :, :],
                           neg)  # (C, ph, H, W)
        rowmax = jnp.where(mx.T[None, None, :, :],
                           jnp.max(masked, axis=2)[:, :, None, :],
                           neg)  # (C, ph, pw, W)
        out = jnp.max(rowmax, axis=3)
        return jnp.where(out == neg, 0.0, out)  # empty bins -> 0

    return jax.vmap(one_roi)(rois)


@register_op("_contrib_ROIAlign", aliases=("roi_align",))
def roi_align(data, rois, *, pooled_size, spatial_scale, sample_ratio=-1,
              position_sensitive=False, aligned=False):
    """Reference: src/operator/contrib/roi_align.cc — average of
    bilinear samples per bin (sample_ratio^2 points, default 2x2)."""
    ph, pw = pooled_size
    b, c, h, w = data.shape
    sr = sample_ratio if sample_ratio > 0 else 2
    off = 0.5 if aligned else 0.0

    def bilinear(img, y, x):
        y = jnp.clip(y, 0.0, h - 1.0)
        x = jnp.clip(x, 0.0, w - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = y - y0
        wx = x - x0
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1]
        v10 = img[:, y1, x0]
        v11 = img[:, y1, x1]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - off
        y1 = roi[2] * spatial_scale - off
        x2 = roi[3] * spatial_scale - off
        y2 = roi[4] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w = rw / pw
        bin_h = rh / ph
        img = data[bidx]
        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        sy = jnp.arange(sr, dtype=jnp.float32)
        ys = y1 + (iy[:, None] + (sy[None, :] + 0.5) / sr) * bin_h
        xs = x1 + (ix[:, None] + (sy[None, :] + 0.5) / sr) * bin_w
        # (ph, sr) x (pw, sr) grids
        yy = ys[:, None, :, None]  # (ph, 1, sr, 1)
        xx = xs[None, :, None, :]  # (1, pw, 1, sr)
        yg = jnp.broadcast_to(yy, (ph, pw, sr, sr)).reshape(-1)
        xg = jnp.broadcast_to(xx, (ph, pw, sr, sr)).reshape(-1)
        vals = jax.vmap(lambda y, x: bilinear(img, y, x))(yg, xg)
        vals = vals.reshape(ph, pw, sr * sr, c).mean(axis=2)
        out = jnp.transpose(vals, (2, 0, 1))  # (C, ph, pw)
        if position_sensitive:
            # R-FCN: input channel layout (out_c, ph, pw); bin (i, j) of
            # output channel k reads input channel k*ph*pw + i*pw + j
            out_c = c // (ph * pw)
            grouped = out.reshape(out_c, ph, pw, ph, pw)
            iy2 = jnp.arange(ph)
            ix2 = jnp.arange(pw)
            out = grouped[:, iy2[:, None], ix2[None, :],
                          iy2[:, None], ix2[None, :]]
        return out

    return jax.vmap(one_roi)(rois)


@register_op("_contrib_Proposal", aliases=("_contrib_proposal",),
             differentiable=False)
def proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """Reference: src/operator/contrib/proposal.cc (RPN proposals).

    cls_prob (B, 2*A, H, W), bbox_pred (B, 4*A, H, W), im_info (B, 3)
    -> rois (B*post_nms_top_n, 5) [batch_idx, x1, y1, x2, y2].
    """
    bsz, _, h, w = cls_prob.shape
    a = len(scales) * len(ratios)
    base = float(feature_stride)
    # generate base anchors (centered at (stride-1)/2 like the reference)
    ctr = (base - 1) / 2
    anchors = []
    for r in ratios:
        size = base * base
        ws = jnp.round(jnp.sqrt(size / r))
        hs = jnp.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([ctr - (wss - 1) / 2, ctr - (hss - 1) / 2,
                            ctr + (wss - 1) / 2, ctr + (hss - 1) / 2])
    base_anchors = jnp.array(anchors, jnp.float32)  # (A, 4)
    shift_x = jnp.arange(w, dtype=jnp.float32) * base
    shift_y = jnp.arange(h, dtype=jnp.float32) * base
    sy, sx = jnp.meshgrid(shift_y, shift_x, indexing="ij")
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)
    all_anchors = (base_anchors[None] + shifts).reshape(-1, 4)  # (HWA, 4)

    def one(cp, bp, info):
        scores = cp[a:].transpose(1, 2, 0).reshape(-1)  # fg scores
        deltas = bp.transpose(1, 2, 0).reshape(-1, 4)
        aw = all_anchors[:, 2] - all_anchors[:, 0] + 1
        ah = all_anchors[:, 3] - all_anchors[:, 1] + 1
        ax = all_anchors[:, 0] + aw * 0.5
        ay = all_anchors[:, 1] + ah * 0.5
        cx = deltas[:, 0] * aw + ax
        cy = deltas[:, 1] * ah + ay
        cw = jnp.exp(deltas[:, 2]) * aw
        ch = jnp.exp(deltas[:, 3]) * ah
        boxes = jnp.stack([cx - cw / 2, cy - ch / 2, cx + cw / 2,
                           cy + ch / 2], axis=-1)
        boxes = jnp.clip(boxes, 0.0,
                         jnp.array([info[1] - 1, info[0] - 1,
                                    info[1] - 1, info[0] - 1]))
        min_sz = rpn_min_size * info[2]  # scaled coords (reference
        # proposal.cc FilterBox: min_size * im_info[2])
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_sz)
                   & (boxes[:, 3] - boxes[:, 1] + 1 >= min_sz))
        scores = jnp.where(keep_sz, scores, -jnp.inf)
        keep, order = _nms_scan(boxes, scores,
                                jnp.zeros(scores.shape, jnp.int32),
                                jnp.isfinite(scores), threshold, True,
                                rpn_pre_nms_top_n)
        sboxes = jnp.take(boxes, order, axis=0)
        rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
        out = jnp.zeros((rpn_post_nms_top_n, 4), jnp.float32)
        sel = keep & (rank < rpn_post_nms_top_n)
        out = out.at[jnp.where(sel, rank, rpn_post_nms_top_n)
                     .clip(0, rpn_post_nms_top_n - 1)].set(
            jnp.where(sel[:, None], sboxes, 0.0)[..., :],
            mode="drop")
        sscores = jnp.take(scores, order)
        out_s = jnp.zeros((rpn_post_nms_top_n,), jnp.float32)
        out_s = out_s.at[jnp.where(sel, rank, rpn_post_nms_top_n)
                         .clip(0, rpn_post_nms_top_n - 1)].set(
            jnp.where(sel, sscores, 0.0), mode="drop")
        return out, out_s

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    bidx = jnp.repeat(jnp.arange(bsz, dtype=jnp.float32),
                      rpn_post_nms_top_n)
    rois = jnp.concatenate([bidx[:, None],
                            boxes.reshape(-1, 4)], axis=-1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois
