"""Declarative operator registry — the TPU-native analog of NNVM op registration.

Reference parity: every reference op is an ``nnvm::Op`` with attribute maps
(``NNVM_REGISTER_OP`` + FInferShape/FInferType/FCompute<cpu|gpu>/FGradient,
see include/mxnet/op_attr_types.h:293 and SURVEY.md §2.3).  On TPU none of
those attributes need to exist separately: an op is a *pure JAX-traceable
function* — shape/dtype inference is jax.eval_shape, FCompute is the function
itself (XLA compiles it for any backend), and FGradient is jax.vjp.

The registry is consumed by:
  * ``mxnet_tpu.ndarray`` — generates eager ``mx.nd.*`` wrappers
    (reference: python/mxnet/ndarray/register.py:116 generated code);
  * ``mxnet_tpu.symbol`` — generates graph-building ``mx.sym.*`` wrappers;
  * the executor/CachedOp paths, which trace the same functions under jit.
"""
from __future__ import annotations

import dataclasses
import inspect
from typing import Callable, Optional

from ..base import MXNetError

__all__ = ["OpDef", "register_op", "get_op", "list_ops", "alias_op"]

_OPS: dict[str, "OpDef"] = {}


@dataclasses.dataclass
class OpDef:
    """One operator.

    fn: pure function (jax arrays in, jax array or tuple out); keyword
        arguments are the op's hyper-parameters (reference: dmlc::Parameter
        structs).
    num_outputs: static output count, or a callable(params)->int for ops
        whose arity depends on hyper-params (e.g. split, BatchNorm).
    differentiable: False for ops with no meaningful gradient (argmax, ...);
        autograd will treat their outputs as constants.
    key_param: name of an implicit PRNG-key parameter; the dispatcher
        injects a fresh key (random ops, Dropout).
    """

    name: str
    fn: Callable
    num_outputs: object = 1
    differentiable: bool = True
    key_param: Optional[str] = None
    train_param: Optional[str] = None  # injected with autograd.is_training()
    #: op picks between a Pallas kernel and plain jnp by target platform
    #: (ops/pallas_conv.py): the eager dispatcher must pin the trace-
    #: platform hint from its concrete inputs around vjp tracing
    platform_sensitive: bool = False
    doc: str = ""

    def out_count(self, params) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(params)
        return self.num_outputs

    @property
    def param_names(self):
        sig = inspect.signature(self.fn)
        return [
            p.name
            for p in sig.parameters.values()
            if p.kind is inspect.Parameter.KEYWORD_ONLY
        ]


def register_op(name=None, *, aliases=(), num_outputs=1, differentiable=True,
                key_param=None, train_param=None, platform_sensitive=False):
    """Decorator: register a pure function as an operator.

    Positional (or *args) parameters are tensor inputs; keyword-only
    parameters are hyper-parameters.
    """

    def _do(fn):
        opname = name or fn.__name__
        op = OpDef(
            name=opname,
            fn=fn,
            num_outputs=num_outputs,
            differentiable=differentiable,
            key_param=key_param,
            train_param=train_param,
            platform_sensitive=platform_sensitive,
            doc=fn.__doc__ or "",
        )
        if opname in _OPS:
            raise MXNetError(f"duplicate op registration: {opname}")
        _OPS[opname] = op
        for a in aliases:
            _OPS[a] = op
        return fn

    return _do


def alias_op(existing: str, *aliases: str):
    op = get_op(existing)
    for a in aliases:
        _OPS[a] = op


def get_op(name: str) -> OpDef:
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"operator '{name}' not registered") from None


def list_ops():
    return sorted(_OPS)
