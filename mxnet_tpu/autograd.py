"""Imperative autograd: record()/pause()/backward() over a VJP tape.

Reference parity: python/mxnet/autograd.py (record/pause scopes :93-146,
mark_variables :197, backward :246, grad) and the C++ tape in
src/imperative/imperative.cc (RecordOp :193 attaches AGInfo to nnvm nodes,
Backward :280 builds the gradient graph with the nnvm Gradient pass).

TPU-native redesign: there is no nnvm graph.  While recording, every op
dispatch runs through ``jax.vjp`` and the returned pull-back closure *is*
the tape node — residuals live in device buffers managed by JAX, and
``backward`` simply walks the tape in reverse topological order calling the
stored pull-backs.  Gradient *computation* therefore runs as compiled XLA
programs (each vjp is jit-compiled at the op/cached-op granularity), and
the Python walk only sequences them — the analog of the reference pushing
backward ops onto its dependency engine.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as onp

from .base import MXNetError

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "mark_variables",
    "backward",
    "grad",
    "get_symbol",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _State()


def is_recording():
    return _STATE.recording


def is_training():
    return _STATE.training


def set_recording(is_record):
    prev = _STATE.recording
    _STATE.recording = bool(is_record)
    return prev


def set_training(train_mode):
    prev = _STATE.training
    _STATE.training = bool(train_mode)
    return prev


class _Scope:
    def __init__(self, recording, training):
        self._recording = recording
        self._training = training

    def __enter__(self):
        self._prev_r = (
            set_recording(self._recording)
            if self._recording is not None
            else None
        )
        self._prev_t = (
            set_training(self._training) if self._training is not None else None
        )
        return self

    def __exit__(self, *exc):
        if self._recording is not None:
            _STATE.recording = self._prev_r
        if self._training is not None:
            _STATE.training = self._prev_t

    # allow use as decorator, like the reference's _RecordingStateScope
    def __call__(self, fn):
        def wrapped(*a, **k):
            with _Scope(self._recording, self._training):
                return fn(*a, **k)

        return wrapped


def record(train_mode=True):
    """Scope in which op invocations are taped (reference autograd.py:122)."""
    return _Scope(True, train_mode)


def pause(train_mode=False):
    return _Scope(False, train_mode)


def train_mode():
    return _Scope(None, True)


def predict_mode():
    return _Scope(None, False)


class TapeNode:
    """One recorded op application: holds the vjp pull-back and the input
    NDArrays (the reference's AGInfo, imperative.h:53-90).

    ``prim_fn``/``all_inputs`` additionally capture the pure primal
    function and EVERY input (incl. non-differentiable ones, as NDArray
    refs or raw jax values) so ``grad(..., create_graph=True)`` can
    replay the subgraph functionally and differentiate it again."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "op_name", "prim_fn",
                 "all_inputs")

    def __init__(self, vjp_fn, inputs, out_avals, op_name="",
                 prim_fn=None, all_inputs=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list of NDArray (or None for non-diff inputs)
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.op_name = op_name
        self.prim_fn = prim_fn
        self.all_inputs = all_inputs


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference autograd.py:197)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._grad = g if req != "null" else None
        var._grad_req = req
        var._is_var = True


def _zeros(aval):
    shape, dtype = aval
    return jnp.zeros(shape, dtype)


def _toposort(heads):
    """Reverse-topological order of tape nodes reachable from heads."""
    order, seen = [], set()
    stack = []
    for h in heads:
        if h._node is not None and id(h._node) not in seen:
            stack.append((h._node, False))
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for inp in node.inputs:
            if inp is not None and inp._node is not None and id(inp._node) not in seen:
                stack.append((inp._node, False))
    return order  # already reverse-topological w.r.t. dependency (children first)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run reverse-mode through the tape starting at `heads`.

    Matches mxnet.autograd.backward semantics: accumulates into the .grad
    buffers attached by attach_grad/mark_variables, honoring grad_req.
    """
    from .ndarray import NDArray  # cycle: autograd <-> ndarray

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    if len(head_grads) != len(heads):
        raise MXNetError("heads and head_grads length mismatch")

    # cotangent accumulator keyed by (tape node id, output index)
    cot: dict[tuple[int, int], object] = {}
    written: set[int] = set()  # vars whose .grad was written this pass
    nodes_by_id = {}
    for h, hg in zip(heads, head_grads):
        if h._node is None:
            if getattr(h, "_is_var", False) and h._grad is not None:
                g = hg._data if hg is not None else jnp.ones(h.shape, h.dtype)
                _accum_var_grad(h, g, written)
                continue
            raise MXNetError(
                "cannot differentiate a head that was not computed under "
                "autograd.record()"
            )
        key = (id(h._node), h._oidx)
        g = hg._data if hg is not None else jnp.ones(h.shape, h.dtype)
        cot[key] = cot[key] + g if key in cot else g
        nodes_by_id[id(h._node)] = h._node

    order = _toposort(heads)
    # order is child-first; we need heads-first (reverse topological):
    for node in reversed(order):
        nid = id(node)
        outs = tuple(
            cot.get((nid, i), None) for i in range(len(node.out_avals))
        )
        if all(o is None for o in outs):
            continue
        outs = tuple(
            o if o is not None else _zeros(av)
            for o, av in zip(outs, node.out_avals)
        )
        if len(node.out_avals) == 1:
            in_grads = node.vjp_fn(outs[0])
        else:
            in_grads = node.vjp_fn(outs)
        for inp, g in zip(node.inputs, in_grads):
            if inp is None or g is None:
                continue
            if getattr(g, "dtype", None) == jax.dtypes.float0:
                continue
            if inp._node is not None:
                k = (id(inp._node), inp._oidx)
                cot[k] = cot[k] + g if k in cot else g
            if getattr(inp, "_is_var", False) and inp._grad is not None:
                _accum_var_grad(inp, g, written)
        if not retain_graph:
            node.vjp_fn = None  # free residuals

    if not retain_graph:
        for h in heads:
            h._node = None


def _accum_var_grad(var, g, written):
    """grad_req='write': overwrite on first contribution of this backward
    pass, accumulate within the pass; 'add': always accumulate (reference
    semantics, include/mxnet/op_attr_types.h OpReqType)."""
    g = g.astype(var._grad.dtype)
    if getattr(var, "_grad_req", "write") == "add" or id(var) in written:
        var._grad._data = var._grad._data + g
    else:
        var._grad._data = g
        written.add(id(var))
    var._fresh_grad = True


def _grad_create_graph(heads, variables, head_grads):
    """Higher-order grad: replay the recorded subgraph as a pure jax
    function of the variables, vjp it, and tape the resulting gradient
    computation so it can be differentiated again (to any order).

    The reference builds the gradient *graph* with the nnvm Gradient
    pass (imperative.cc:280) so grad-of-grad falls out of graph
    composition; here the tape's stored primal closures are replayed
    under jax tracing, which is the functional equivalent.  Uses the
    input values captured at record time — mutating an input between
    recording and grad() is undefined (same caveat as the reference's
    in-place writes invalidating AGInfo).
    """
    from .ndarray import NDArray

    order = _toposort(heads)
    for node in order:
        if node.prim_fn is None or node.all_inputs is None:
            raise MXNetError(
                f"create_graph=True: node {node.op_name!r} was recorded "
                "without replay info")
    var_list = list(variables)
    var_pos = {id(v): i for i, v in enumerate(var_list)}

    def replay(*vvals):
        env = {}

        def value_of(x):
            if not isinstance(x, NDArray):
                return x  # raw jax value captured at record time
            if id(x) in var_pos:
                return vvals[var_pos[id(x)]]
            n = getattr(x, "_node", None)
            if n is not None and (id(n), x._oidx) in env:
                return env[(id(n), x._oidx)]
            return x._data

        for node in order:  # child-first == dependencies before users
            outs = node.prim_fn(*[value_of(i) for i in node.all_inputs])
            outs = (outs,) if not isinstance(outs, (tuple, list)) \
                else tuple(outs)
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
        return tuple(value_of(h) for h in heads)

    hg = tuple(
        (g._data if isinstance(g, NDArray) else jnp.asarray(g))
        if g is not None else jnp.ones(h.shape, h.dtype)
        for h, g in zip(heads, head_grads))

    single_var = len(var_list) == 1

    def grads_of(*vvals):
        _, pull = jax.vjp(replay, *vvals)
        gs = pull(hg)
        # Single-variable: return a bare value so the taped node has one
        # output and backward()'s len(out_avals)==1 convention (bare
        # cotangent, not a 1-tuple) matches second_vjp's expectation.
        return gs[0] if single_var else gs

    vvals = tuple(v._data for v in var_list)
    grad_vals, second_vjp = jax.vjp(grads_of, *vvals)
    if single_var:
        grad_vals = (grad_vals,)
    out = [NDArray(g) for g in grad_vals]
    if is_recording():
        node = TapeNode(
            second_vjp,
            [v if (getattr(v, "_is_var", False) or v._node is not None)
             else None for v in var_list],
            [(g.shape, g.dtype) for g in grad_vals],
            op_name="_grad_of_grad",
            prim_fn=grads_of,
            all_inputs=list(var_list),
        )
        for i, o in enumerate(out):
            o._node = node
            o._oidx = i
    return out


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Functional gradient API (reference autograd.py grad())."""
    from .ndarray import NDArray

    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if create_graph:
        hs = [heads] if isinstance(heads, NDArray) else list(heads)
        if head_grads is None:
            hgs = [None] * len(hs)
        elif isinstance(head_grads, NDArray):
            hgs = [head_grads]
        else:
            hgs = list(head_grads)
        if len(hgs) != len(hs):
            raise MXNetError("heads and head_grads length mismatch")
        grads = _grad_create_graph(hs, variables, hgs)
        return grads[0] if single else grads
    saved = [
        (v._grad, getattr(v, "_grad_req", "write"), getattr(v, "_is_var", False))
        for v in variables
    ]
    from .ndarray import zeros

    for v in variables:
        v._grad = zeros(v.shape, dtype=v.dtype, ctx=v.context)
        v._grad_req = "write"
        v._is_var = True
    backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
    grads = [v._grad for v in variables]
    for v, (g, req, isv) in zip(variables, saved):
        v._grad, v._grad_req, v._is_var = g, req, isv
    return grads[0] if single else grads


def get_symbol(x):
    raise MXNetError(
        "autograd.get_symbol is not supported: the TPU build has no nnvm "
        "graph; use gluon HybridBlock.export or mx.sym instead"
    )
