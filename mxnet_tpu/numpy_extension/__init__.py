"""mx.npx — numpy_extension: operators outside the NumPy standard.

Reference parity: python/mxnet/numpy_extension/ + the ``npx`` namespace
(set_np/reset_np semantics flags in python/mxnet/util.py, nn ops like
npx.softmax/convolution routed to the shared op registry).
"""
from __future__ import annotations

from .. import ndarray as _nd
from ..ndarray.ndarray import invoke, waitall  # noqa: F401
from ..numpy.multiarray import _f, _np
from ..util import is_np_array, set_np, use_np  # noqa: F401


def reset_np():
    """Reference: util.py reset_np — leave numpy semantics."""
    set_np(shape=False, array=False)


def seed(s):
    from .. import random as _random

    _random.seed(s)


def softmax(data, axis=-1, length=None, temperature=None):
    return _f("softmax", data, axis=axis, temperature=temperature)


def log_softmax(data, axis=-1):
    return _f("log_softmax", data, axis=axis)


def relu(data):
    return _f("relu", data)


def sigmoid(data):
    return _f("sigmoid", data)


def activation(data, act_type="relu"):
    return _f("Activation", data, act_type=act_type)


def leaky_relu(data, act_type="leaky", slope=0.25):
    return _f("LeakyReLU", data, act_type=act_type, slope=slope)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-3,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               axis=1):
    return _f("BatchNorm", x, gamma, beta, running_mean, running_var,
              eps=eps, momentum=momentum, fix_gamma=fix_gamma,
              use_global_stats=use_global_stats, axis=axis)


def convolution(data, weight, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=1, num_group=1,
                no_bias=False, layout=None):
    args = [data, weight] + ([bias] if bias is not None else [])
    return _f("Convolution", *args, kernel=kernel, stride=stride,
              dilate=dilate, pad=pad, num_filter=num_filter,
              num_group=num_group, no_bias=no_bias or bias is None,
              layout=layout)


def fully_connected(x, weight, bias=None, num_hidden=1, no_bias=False,
                    flatten=True):
    args = [x, weight] + ([bias] if bias is not None else [])
    return _f("FullyConnected", *args, num_hidden=num_hidden,
              no_bias=no_bias or bias is None, flatten=flatten)


def pooling(data, kernel=(1, 1), stride=None, pad=None, pool_type="max",
            global_pool=False):
    return _f("Pooling", data, kernel=kernel, stride=stride, pad=pad,
              pool_type=pool_type, global_pool=global_pool)


def dropout(data, p=0.5, mode="training"):
    return _f("Dropout", data, p=p, mode=mode)


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _f("one_hot", data, depth=depth, on_value=on_value,
              off_value=off_value, dtype=dtype)


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    return _f("pick", data, index, axis=axis, mode=mode,
              keepdims=keepdims)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    return _f("topk", data, axis=axis, k=k, ret_typ=ret_typ,
              is_ascend=is_ascend, dtype=dtype)


def reshape_like(lhs, rhs):
    return _f("reshape_like", lhs, rhs)


def arange_like(data, start=0.0, step=1.0, axis=None):
    import jax.numpy as jnp

    from ..numpy.multiarray import _direct, _in

    a = _in(data)
    if axis is None:
        n = a.size
    else:
        n = a.shape[axis]
    return _direct(lambda: jnp.arange(start, start + step * n, step,
                                      dtype=jnp.float32))


def gamma(data):
    return _f("gamma", data)


def gammaln(data):
    return _f("gammaln", data)


def erf(data):
    return _f("erf", data)


def erfinv(data):
    return _f("erfinv", data)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    args = [data] + ([sequence_length]
                     if sequence_length is not None else [])
    return _f("SequenceMask", *args,
              use_sequence_length=use_sequence_length, value=value,
              axis=axis)


def load(fname):
    return {k: _np(v) for k, v in _nd.load(fname).items()}


def save(fname, data):
    if isinstance(data, dict):
        _nd.save(fname, {k: v for k, v in data.items()})
    else:
        _nd.save(fname, data)
