"""mx.npx — numpy_extension: operators outside the NumPy standard.

Reference parity: python/mxnet/numpy_extension/ + the ``npx`` namespace
(set_np/reset_np semantics flags in python/mxnet/util.py, nn ops like
npx.softmax/convolution routed to the shared op registry).
"""
from __future__ import annotations

from .. import ndarray as _nd
from ..ndarray.ndarray import invoke, waitall  # noqa: F401
from ..numpy.multiarray import _f, _np
from ..util import is_np_array, set_np, use_np  # noqa: F401


def reset_np():
    """Reference: util.py reset_np — leave numpy semantics."""
    set_np(shape=False, array=False)


def seed(s):
    from .. import random as _random

    _random.seed(s)


def softmax(data, axis=-1, length=None, temperature=None):
    return _f("softmax", data, axis=axis, temperature=temperature)


def log_softmax(data, axis=-1):
    return _f("log_softmax", data, axis=axis)


def relu(data):
    return _f("relu", data)


def sigmoid(data):
    return _f("sigmoid", data)


def activation(data, act_type="relu"):
    return _f("Activation", data, act_type=act_type)


def leaky_relu(data, act_type="leaky", slope=0.25):
    return _f("LeakyReLU", data, act_type=act_type, slope=slope)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-3,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               axis=1):
    return _f("BatchNorm", x, gamma, beta, running_mean, running_var,
              eps=eps, momentum=momentum, fix_gamma=fix_gamma,
              use_global_stats=use_global_stats, axis=axis)


def convolution(data, weight, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=1, num_group=1,
                no_bias=False, layout=None):
    args = [data, weight] + ([bias] if bias is not None else [])
    return _f("Convolution", *args, kernel=kernel, stride=stride,
              dilate=dilate, pad=pad, num_filter=num_filter,
              num_group=num_group, no_bias=no_bias or bias is None,
              layout=layout)


def fully_connected(x, weight, bias=None, num_hidden=1, no_bias=False,
                    flatten=True):
    args = [x, weight] + ([bias] if bias is not None else [])
    return _f("FullyConnected", *args, num_hidden=num_hidden,
              no_bias=no_bias or bias is None, flatten=flatten)


def pooling(data, kernel=(1, 1), stride=None, pad=None, pool_type="max",
            global_pool=False):
    return _f("Pooling", data, kernel=kernel, stride=stride, pad=pad,
              pool_type=pool_type, global_pool=global_pool)


def dropout(data, p=0.5, mode="training"):
    return _f("Dropout", data, p=p, mode=mode)


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _f("one_hot", data, depth=depth, on_value=on_value,
              off_value=off_value, dtype=dtype)


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    return _f("pick", data, index, axis=axis, mode=mode,
              keepdims=keepdims)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    return _f("topk", data, axis=axis, k=k, ret_typ=ret_typ,
              is_ascend=is_ascend, dtype=dtype)


def reshape_like(lhs, rhs):
    return _f("reshape_like", lhs, rhs)


def arange_like(data, start=0.0, step=1.0, axis=None):
    import jax.numpy as jnp

    from ..numpy.multiarray import _direct, _in

    a = _in(data)
    if axis is None:
        n = a.size
    else:
        n = a.shape[axis]
    return _direct(lambda: jnp.arange(start, start + step * n, step,
                                      dtype=jnp.float32))


def gamma(data):
    return _f("gamma", data)


def gammaln(data):
    return _f("gammaln", data)


def erf(data):
    return _f("erf", data)


def erfinv(data):
    return _f("erfinv", data)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    args = [data] + ([sequence_length]
                     if sequence_length is not None else [])
    return _f("SequenceMask", *args,
              use_sequence_length=use_sequence_length, value=value,
              axis=axis)


def load(fname):
    return {k: _np(v) for k, v in _nd.load(fname).items()}


def save(fname, data):
    if isinstance(data, dict):
        _nd.save(fname, {k: v for k, v in data.items()})
    else:
        _nd.save(fname, data)


# round 3: remaining npx surface (reference numpy_extension/_op.py)
def is_np_shape():
    return is_np_array()


def use_np_shape(fn):
    return use_np(fn)


def use_np_array(fn):
    return use_np(fn)


def masked_softmax(data, mask=None, axis=-1, temperature=None):
    import jax.numpy as jnp

    from ..numpy.multiarray import _direct

    if mask is None:
        return softmax(data, axis=axis, temperature=temperature)
    t = 1.0 if temperature is None else float(temperature)

    def f(d, m):
        neg = jnp.finfo(d.dtype).min
        return jax_softmax(jnp.where(m.astype(bool), d / t, neg), axis)

    import jax

    def jax_softmax(v, ax):
        return jax.nn.softmax(v, axis=ax)

    return _direct(f, data, mask)


def masked_log_softmax(data, mask=None, axis=-1, temperature=None):
    import jax

    from ..numpy.multiarray import _direct

    if mask is None:
        return log_softmax(data, axis=axis)
    t = 1.0 if temperature is None else float(temperature)

    def f(d, m):
        import jax.numpy as jnp

        neg = jnp.finfo(d.dtype).min
        return jax.nn.log_softmax(
            jnp.where(m.astype(bool), d / t, neg), axis=axis)

    return _direct(f, data, mask)


def deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, num_filter=1,
                  num_group=1, no_bias=True, layout=None):
    args = [data, weight] + ([bias] if bias is not None else [])
    return _f("Deconvolution", *args, kernel=kernel, stride=stride,
              dilate=dilate, pad=pad, adj=adj, num_filter=num_filter,
              num_group=num_group, no_bias=no_bias or bias is None,
              layout=layout)


def rnn(data, parameters, state, state_cell=None, mode="lstm",
        state_size=1, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=False, projection_size=None):
    args = [data, parameters, state] + (
        [state_cell] if state_cell is not None else [])
    return _f("RNN", *args, mode=mode, state_size=state_size,
              num_layers=num_layers, bidirectional=bidirectional, p=p,
              state_outputs=state_outputs,
              projection_size=projection_size)


def embedding(data, weight, input_dim=1, output_dim=1, dtype="float32",
              sparse_grad=False):
    return _f("Embedding", data, weight, input_dim=input_dim,
              output_dim=output_dim, dtype=dtype)


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _f("LayerNorm", data, gamma, beta, axis=axis, eps=eps)


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    return _f("batch_dot", a, b, transpose_a=transpose_a,
              transpose_b=transpose_b)


def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    return _f("broadcast_like", lhs, rhs, lhs_axes=lhs_axes,
              rhs_axes=rhs_axes)


def shape_array(data):
    return _f("shape_array", data)


def smooth_l1(data, scalar=1.0):
    return _f("smooth_l1", data, scalar=scalar)


def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    return _f("_contrib_MultiBoxPrior", data, sizes=sizes, ratios=ratios,
              clip=clip, steps=steps, offsets=offsets)


def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    return _f("_contrib_MultiBoxTarget", anchor, label, cls_pred,
              overlap_threshold=overlap_threshold,
              ignore_label=ignore_label,
              negative_mining_ratio=negative_mining_ratio,
              negative_mining_thresh=negative_mining_thresh,
              minimum_negative_samples=minimum_negative_samples,
              variances=variances)


def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0,
                       nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    return _f("_contrib_MultiBoxDetection", cls_prob, loc_pred, anchor,
              clip=clip, threshold=threshold, background_id=background_id,
              nms_threshold=nms_threshold, force_suppress=force_suppress,
              variances=variances, nms_topk=nms_topk)


def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    return _f("ROIPooling", data, rois, pooled_size=pooled_size,
              spatial_scale=spatial_scale)
