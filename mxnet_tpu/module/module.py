"""Module: executor-backed trainable module.

Reference parity: python/mxnet/module/module.py (``Module`` :40 over
``DataParallelExecutorGroup``).  TPU-native: ONE executor, ONE compiled
SPMD program.  ``context=[gpu(0)..gpu(N-1)]`` builds a 1-D 'data' mesh
over those chips: batch args shard over it, params/aux replicate, and
XLA inserts the gradient all-reduce — the reference's
DataParallelExecutorGroup (executor_group.py:144 batch slicing, :304
grad reduce) collapses into sharding annotations.  BatchNorm under the
mesh computes GLOBAL batch stats (collectives inside the jitted graph),
i.e. SyncBatchNorm semantics — stricter than the reference's per-device
stats.
"""
from __future__ import annotations

import logging

import numpy as onp

from .. import initializer as init_mod
from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError
from ..context import cpu
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names) if data_names else []
        self._label_names = list(label_names) if label_names else []
        self._context = context or cpu()
        self._mesh = None
        if isinstance(self._context, (list, tuple)):
            ctxs = list(self._context)
            self._context = ctxs[0]
            if len(ctxs) > 1:
                import jax
                from jax.sharding import Mesh

                devs = [c.jax_device() for c in ctxs]
                if len(set(devs)) != len(devs):
                    raise MXNetError(
                        f"context list {ctxs} resolves to duplicate "
                        "devices — data parallelism needs distinct chips")
                self._mesh = Mesh(onp.array(devs), ("data",))
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [
            n for n in arg_names
            if n not in self._data_names and n not in self._label_names
        ]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._optimizer = None
        self._updater = None
        self._arg_params = None  # preloaded checkpoint weights (load())
        self._aux_params = None
        self._grad_req = None
        self._monitor = None
        # reference group2ctxs: one group->ctx dict per data-parallel
        # context; the TPU Module runs ONE executor, so a single dict
        # (or a 1-element list of dicts) maps groups to devices
        if isinstance(group2ctxs, (list, tuple)):
            if len(group2ctxs) > 1:
                raise MXNetError(
                    "group2ctxs: the TPU Module is one SPMD executor — "
                    "pass one group->Context dict (data parallelism "
                    "comes from context=[...], not per-ctx groups)")
            group2ctxs = group2ctxs[0] if group2ctxs else None
        self._group2ctx = group2ctxs

    # ------------------------------------------------------- descriptors
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        self._check_binded()
        shape_kwargs = {n: tuple(s) for n, s in self._data_shapes}
        if self._label_shapes:
            shape_kwargs.update(
                {n: tuple(s) for n, s in self._label_shapes})
        _, out_shapes, _ = self._symbol.infer_shape_partial(
            **shape_kwargs)
        return list(zip(self._symbol.list_outputs(), out_shapes))

    # ------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        # persistent compilation cache (no-op unless
        # JAX_COMPILATION_CACHE_DIR is set): a re-bind of a shape
        # already compiled — the common restart/recapture path — loads
        # the XLA executable from disk instead of recompiling
        from ..config import setup_compilation_cache

        setup_compilation_cache()
        self.for_training = for_training
        self._data_shapes = [(d[0], tuple(d[1])) for d in data_shapes]
        self._label_shapes = ([(d[0], tuple(d[1]))
                               for d in label_shapes]
                              if label_shapes else None)
        shape_kwargs = {}
        for desc in data_shapes:
            name, shape = desc[0], desc[1]
            shape_kwargs[name] = tuple(shape)
        if label_shapes:
            for desc in label_shapes:
                name, shape = desc[0], desc[1]
                shape_kwargs[name] = tuple(shape)
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names:
                req[n] = "write" if inputs_need_grad else "null"
            elif n in self._label_names or n in self._fixed_param_names:
                req[n] = "null"
            else:
                req[n] = grad_req if for_training else "null"
        self._grad_req = req
        self._exec = self._symbol.simple_bind(
            self._context, grad_req=req, group2ctx=self._group2ctx,
            **shape_kwargs)
        if self._mesh is not None:
            if self._group2ctx:
                raise MXNetError("group2ctxs cannot combine with a "
                                 "multi-context data mesh")
            self._place_on_mesh()
        self.binded = True
        if self._monitor is not None:
            self._monitor.install(self._exec)
        if shared_module is not None and shared_module._exec is not None:
            # share the actual parameter NDArray objects (reference:
            # shared_exec memory pool, bucketing_module.py) — an update
            # through any bucket is visible to all
            for n in self._param_names:
                if n in shared_module._exec.arg_dict:
                    self._exec.arg_dict[n] = \
                        shared_module._exec.arg_dict[n]
            for n in self._aux_names:
                if n in shared_module._exec.aux_dict:
                    self._exec.aux_dict[n] = \
                        shared_module._exec.aux_dict[n]
            self._exec.arg_arrays = [
                self._exec.arg_dict[n]
                for n in self._symbol.list_arguments()]
            self._exec.aux_arrays = [
                self._exec.aux_dict[n] for n in self._aux_names]
            if shared_module.params_initialized:
                self.params_initialized = True
        if self._arg_params is not None:
            # apply weights preloaded by Module.load (reference: load
            # stashes arg/aux params and bind installs them)
            self.init_params(arg_params=self._arg_params,
                             aux_params=self._aux_params,
                             force_init=True, allow_missing=True)

    # ------------------------------------------------------ mesh support
    def _data_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._mesh, P("data"))

    def _place_on_mesh(self):
        """Replicate params/aux/grads over the data mesh; batch args
        shard at feed time (reference: executor_group.py:144 slices the
        batch across contexts — here the sharding annotation does it)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self._mesh, P())
        batch_names = set(self._data_names) | set(self._label_names)
        for store in (self._exec.arg_dict, self._exec.aux_dict,
                      self._exec.grad_dict):
            for n, v in store.items():
                if n in batch_names:
                    continue
                v._data = jax.device_put(v._data, repl)

    def _shard_batch(self, name, arr):
        import jax

        n_dev = self._mesh.devices.size
        if arr.shape[0] % n_dev:
            raise MXNetError(
                f"batch axis of '{name}' ({arr.shape[0]}) must divide "
                f"the {n_dev}-device data mesh")
        return jax.device_put(arr, self._data_sharding())

    # ----------------------------------------------------------- params
    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        self._check_binded()
        if self.params_initialized and not force_init:
            return
        if initializer is None and (arg_params is None
                                    or aux_params is None):
            initializer = init_mod.Uniform(0.01)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._adopt(self._as_jax(arg_params[name], arr))
            elif initializer is not None:
                val = initializer(init_mod.InitDesc(name), arr.shape,
                                  str(arr.dtype))
                arr._adopt(nd.array(onp.asarray(val))._data)
            elif not allow_missing:
                raise MXNetError(f"missing parameter {name}")
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._adopt(self._as_jax(aux_params[name], arr))
            elif initializer is not None:
                val = initializer(init_mod.InitDesc(name), arr.shape,
                                  str(arr.dtype))
                arr._adopt(nd.array(onp.asarray(val))._data)
        if self._mesh is not None:
            # _adopt swapped in host-placed arrays; restore replication
            self._place_on_mesh()
        self.params_initialized = True

    @staticmethod
    def _as_jax(v, like):
        if isinstance(v, nd.NDArray):
            return v._data.astype(like._data.dtype)
        return nd.array(onp.asarray(v))._data.astype(like._data.dtype)

    def get_params(self):
        self._check_binded()
        arg = {n: self._exec.arg_dict[n].copy()
               for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    # -------------------------------------------------------- optimizer
    def _update_param_names(self):
        """Parameters the optimizer actually updates (grad_req not
        'null' and a gradient buffer exists) — the set the sharded
        bucket plan must cover exactly."""
        return [n for n in self._param_names
                if self._grad_req.get(n, "null") != "null"
                and self._exec.grad_dict.get(n) is not None]

    def _resolve_optimizer_sharding(self, kvstore, optimizer):
        """Map ``kvstore='dist_*'`` (whose reference semantics ARE the
        server-side optimizer on key shards, kvstore_dist_server.h:346)
        to the sharded-server updater over this module's data mesh.
        MXNET_OPTIMIZER_SHARDING overrides in both directions.
        Per-param lr_mult/wd_mult ARE supported (the updater
        partitions buckets by effective (lr, wd)); semantics the flat
        buckets cannot reproduce — per-update lr schedules, stochastic
        rules, multi-precision masters, fused/eager state-layout
        mismatches — fall back to the eager per-param Updater with a
        logged reason."""
        from ..parallel.zero import (resolve_sharding_env,
                                     sharding_rule_reasons)

        env = resolve_sharding_env()
        if env is False:
            return None
        kv_name = kvstore if isinstance(kvstore, str) else \
            getattr(kvstore, "type", "")
        if env != "ps" and not str(kv_name).startswith("dist"):
            return None
        if self._mesh is None:
            return None  # one device: nothing to shard over
        reasons = sharding_rule_reasons(optimizer)
        if reasons:
            self.logger.warning(
                "optimizer sharding requested (kvstore=%r) but falling "
                "back to the replicated updater: %s", kv_name,
                "; ".join(reasons))
            return None
        return "ps"

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._check_binded()
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            # key optimizer state by parameter NAME so the updater can be
            # shared across buckets whose graphs order params differently
            idx2name = {n: n for n in self._param_names}
            opt_params = dict(optimizer_params)
            if "rescale_grad" not in opt_params:
                # reference module.py: default grad rescale is 1/batch
                batch_size = self._exec.arg_dict[
                    self._data_names[0]].shape[0]
                opt_params["rescale_grad"] = 1.0 / batch_size
            optimizer = opt.create(
                optimizer, param_idx2name=idx2name, **opt_params)
        self._optimizer = optimizer
        if self._resolve_optimizer_sharding(kvstore, optimizer) == "ps":
            # ZeRO-1: optimizer state sharded over the data mesh in
            # flat buckets, updates run on the owned shard only, params
            # all-gather back (parallel.zero; the dist_sync
            # server-side-optimizer analog)
            from ..parallel.zero import ShardedBucketUpdater

            upd = {n: self._exec.arg_dict[n]._data
                   for n in self._update_param_names()}
            self._updater = ShardedBucketUpdater(optimizer, self._mesh,
                                                 upd)
        else:
            self._updater = opt.get_updater(optimizer)
        from .. import telemetry
        from ..parallel.zero import ShardedBucketUpdater as _SBU

        rl = telemetry.current()
        if rl is not None:
            # sticky context: every later step record carries the
            # optimizer-sharding mode actually in effect
            rl.set_context(sharding="ps" if isinstance(
                self._updater, _SBU) else "none")
        self.optimizer_initialized = True

    # ------------------------------------------------------------- exec
    def forward(self, data_batch, is_train=None):
        self._check_binded()
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if data_batch.label is not None and self._label_names:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        if self._mesh is not None:
            for name, arr in feeds.items():
                v = arr._data if isinstance(arr, nd.NDArray) else \
                    nd.array(onp.asarray(arr))._data
                feeds[name] = nd.NDArray(self._shard_batch(name, v))
        # rebind on shape change (reference module reshapes executors)
        for k, v in feeds.items():
            if tuple(self._exec.arg_dict[k].shape) != tuple(v.shape):
                self._exec = self._exec.reshape(
                    **{k2: tuple(v2.shape) for k2, v2 in feeds.items()})
                break
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._check_binded()
        self._exec.backward(out_grads=out_grads)

    def update(self):
        self._check_binded()
        assert self.optimizer_initialized
        from ..parallel.zero import ShardedBucketUpdater

        if isinstance(self._updater, ShardedBucketUpdater):
            # one fused sharded program over ALL params (per-name calls
            # would defeat the flat bucketing)
            self._updater.update_all(
                [(n, self._exec.grad_dict[n], self._exec.arg_dict[n])
                 for n in self._update_param_names()])
            return
        for name in self._update_param_names():
            self._updater(name, self._exec.grad_dict[name],
                          self._exec.arg_dict[name])

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    def get_outputs(self, merge_multi_context=True):
        self._check_binded()
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        self._check_binded()
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    # --------------------------------------------------------------- io
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        keep_n=None):
        """One atomic checkpoint version via resilience.checkpoint:
        params, optional optimizer state, symbol, CRC manifest and the
        `latest` pointer land together or not at all (legacy
        `prefix-NNNN.params`/`.states` layout preserved)."""
        from ..resilience.checkpoint import CheckpointManager

        arg_params, aux_params = self.get_params()
        states = None
        if save_optimizer_states:
            states = self._get_optimizer_states()
        CheckpointManager(prefix, keep_n=keep_n).save(
            epoch, symbol=self._symbol, arg_params=arg_params,
            aux_params=aux_params, optimizer_states=states)

    def _step_finite(self):
        """Outputs AND gradients: finite predictions can still carry a
        non-finite gradient (log(0) in the loss backward), and the
        guard's whole point is that such a step must not update."""
        if not self._outputs_finite():
            return False
        for name in self._update_param_names():
            g = self._exec.grad_dict[name]
            if not onp.isfinite(g.asnumpy()).all():
                return False
        return True

    def _named_grads(self):
        """The live gradient buffers by parameter name — the numerics
        monitor's (MXNET_NUMERICS) eager observation point: fit
        summarises these on sampled and bad steps so a NaN step names
        the tensor that went non-finite."""
        return {n: self._exec.grad_dict[n]
                for n in self._update_param_names()}

    def _topology_block(self):
        """The world this module trains in, for the checkpoint
        manifest's ``topology`` stamp: data-mesh width, process count,
        optimizer-sharding mode, the live bucket-plan fingerprint and
        the GLOBAL batch (local batch x process count — the unit the
        resume cursor is kept in, so it re-slices across any world)."""
        from ..parallel.zero import ShardedBucketUpdater
        from ..resilience import elastic

        upd = self._updater if isinstance(
            self._updater, ShardedBucketUpdater) else None
        global_batch = None
        try:
            local_b = int(
                self._exec.arg_dict[self._data_names[0]].shape[0])
            ctx = elastic.context()
            global_batch = local_b * (ctx.num_processes
                                      if ctx is not None else 1)
        except Exception:
            pass
        return elastic.topology_block(
            world_size=upd.n_shards if upd is not None else None,
            mesh=self._mesh,
            sharding="ps" if upd is not None else "none",
            plan=upd.plan if upd is not None else None,
            global_batch=global_batch)

    # optimizer-state hooks for fit's checkpoint/resume plumbing
    def _get_optimizer_states(self):
        if self._updater is None:
            raise MXNetError("optimizer not initialized")
        # dump_optimizer=True: the pickle carries the optimizer with
        # its update COUNTERS (num_update/_index_update_count — and the
        # sharded updater seeds them from its own step count), so a
        # resumed adam/ftml run continues its bias correction at the
        # right t in EITHER mode instead of silently restarting at 1.
        # Both Updater.set_states and ShardedBucketUpdater.set_states
        # accept the (states, optimizer) tuple form.
        return self._updater.get_states(dump_optimizer=True)

    def _set_optimizer_states(self, states):
        if self._updater is None:
            raise MXNetError("optimizer not initialized")
        self._updater.set_states(states)
        # a dump_optimizer pickle makes set_states install the
        # unpickled optimizer as the updater's live one; re-point the
        # module at it so post-resume mutations (the lr-decay callback
        # recipe: module._optimizer.lr = ...) reach the optimizer that
        # actually runs, not a dead pre-resume object
        live = getattr(self._updater, "optimizer", None)
        if live is not None:
            self._optimizer = live

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from .. import model

        sym, arg_params, aux_params = model.load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params, mod._aux_params = arg_params, aux_params
        return mod

    def install_monitor(self, mon):
        """Attach a ``mx.monitor.Monitor`` to this module's executor
        (reference module.py install_monitor -> executor monitor
        callback): every forward records output stats under the
        monitor's tic/toc protocol.  Installs now if bound, else at
        bind."""
        self._monitor = mon
        if self._exec is not None:
            mon.install(self._exec)
