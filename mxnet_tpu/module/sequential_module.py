"""SequentialModule + PythonModule (reference:
python/mxnet/module/sequential_module.py, python_module.py)."""
from __future__ import annotations

from ..base import MXNetError
from .base_module import BaseModule

__all__ = ["SequentialModule", "PythonModule", "PythonLossModule"]


class SequentialModule(BaseModule):
    """Chain modules: each module's outputs feed the next (reference
    sequential_module.py:35)."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=None):
        super().__init__()
        self._modules = []
        self._metas = []
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._label_shapes = None

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        return self

    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        if shared_module is not None:
            raise MXNetError("shared_module not supported in "
                             "SequentialModule")
        self._label_shapes = label_shapes
        cur_shapes = data_shapes
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, False)
            mod.bind(cur_shapes,
                     label_shapes if take_labels else None,
                     for_training=for_training,
                     inputs_need_grad=(inputs_need_grad or i > 0),
                     force_rebind=force_rebind, grad_req=grad_req)
            if i + 1 == len(self._modules):
                break
            # wire this module's outputs into the next module's data
            # slots positionally (reference META_AUTO_WIRING)
            nxt = self._modules[i + 1]
            outs = mod.output_shapes
            if len(nxt.data_names) > len(outs):
                raise MXNetError(
                    f"SequentialModule wiring mismatch: module {i} "
                    f"produces {len(outs)} output(s) but module "
                    f"{i + 1} expects {len(nxt.data_names)} input(s)")
            cur_shapes = [
                (dn, s) for dn, (_, s) in zip(nxt.data_names, outs)]
        self.binded = True

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        for mod in self._modules:
            mod.init_params(initializer=initializer,
                            arg_params=arg_params, aux_params=aux_params,
                            allow_missing=True, force_init=force_init,
                            allow_extra=True)
        self.params_initialized = True

    def get_params(self):
        args, auxs = {}, {}
        for mod in self._modules:
            a, x = mod.get_params()
            args.update(a)
            auxs.update(x)
        return args, auxs

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        for mod in self._modules:
            mod.set_params(arg_params, aux_params, allow_missing=True,
                           force_init=force_init, allow_extra=True)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        from ..io.io import DataBatch

        batch = data_batch
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            mod.forward(batch, is_train=is_train)
            if i == len(self._modules) - 1:
                break
            out = mod.get_outputs()
            label = getattr(data_batch, "label", None)
            batch = DataBatch(data=out, label=label)

    def backward(self, out_grads=None):
        grads = out_grads
        for i, mod in reversed(list(enumerate(self._modules))):
            mod.backward(out_grads=grads)
            if i == 0:
                break
            grads = mod.get_input_grads()

    def update(self):
        for mod in self._modules:
            mod.update()

    def update_metric(self, eval_metric, labels):
        self._modules[-1].update_metric(eval_metric, labels)

    def get_outputs(self):
        return self._modules[-1].get_outputs()

    def get_input_grads(self):
        return self._modules[0].get_input_grads()


class PythonModule(BaseModule):
    """A module whose computation is arbitrary Python (reference
    python_module.py:30) — base for metrics-only / loss-only modules."""

    def __init__(self, data_names, label_names, output_names,
                 logger=None):
        super().__init__()
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    def get_params(self):
        return {}, {}

    def init_params(self, *a, **k):
        self.params_initialized = True

    def init_optimizer(self, *a, **k):
        self.optimizer_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        raise NotImplementedError

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        pass


class PythonLossModule(PythonModule):
    """Pass-through loss head computing gradients in Python (reference
    python_module.py:191)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=None,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger=logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self):
        return [self._scores]

    def backward(self, out_grads=None):
        if self._grad_func is not None:
            self._scores_grad = self._grad_func(self._labels,
                                                self._scores)
        else:
            raise MXNetError("PythonLossModule requires grad_func")

    def get_input_grads(self):
        return [self._scores_grad]
