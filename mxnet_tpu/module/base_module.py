"""BaseModule: the high-level train/predict interface.

Reference parity: python/mxnet/module/base_module.py (``fit`` :409-538 —
bind → init_params → init_optimizer → epoch loop forward_backward /
update / metric / checkpoint; ``score``, ``predict``).
"""
from __future__ import annotations

import logging
import time

import numpy as onp

from .. import metric as metric_mod
from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ------------------------------------------------------ infra props
    @property
    def symbol(self):
        return self._symbol

    def _check_binded(self):
        if not self.binded:
            raise MXNetError("Module not binded")

    # ------------------------------------------------------ train loop
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(_BatchEndParam(epoch, nbatch, eval_metric))
            actual_num_batch += 1
        if score_end_callback:
            for cb in _as_list(score_end_callback):
                cb(_BatchEndParam(epoch, actual_num_batch, eval_metric))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [
                out[0 : out.shape[0] - (pad or 0)]
                for out in self.get_outputs()
            ]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise MXNetError(
                        "Cannot merge batches: different number of outputs")
            output_list2 = [
                nd.concat(*[out[i] for out in output_list], dim=0)
                for i in range(num_outputs)
            ]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None, resume_from=None,
            checkpoint=None, checkpoint_period=1):
        """Full training loop (reference base_module.py:409-538).

        Elastic extensions (resilience subsystem):

        * ``checkpoint`` — a prefix (or CheckpointManager) fit
          checkpoints to: atomically at every ``checkpoint_period``
          epoch boundary, and mid-epoch on a SIGTERM/SIGINT drain.
          Retention follows ``MXNET_CKPT_KEEP`` for prefix arguments.
        * ``resume_from`` — a prefix (or CheckpointManager) to restore
          from: params, optimizer state, host+device RNG and the
          epoch/batch cursor all come back, and the data iterator is
          skipped ahead, so a killed-and-relaunched fit continues
          bit-exactly (given the same seed and a deterministic
          iterator).  Overrides ``arg_params``/``begin_epoch``.
        * a SIGTERM/SIGINT during the epoch loop drains: the in-flight
          step finishes, a final checkpoint flushes (cursor included),
          the device-feed producer closes, and the signal is re-raised.
        * ``MXNET_BAD_STEP_LIMIT`` > 0 arms the step-level NaN/Inf
          guard: non-finite steps are skipped (update withheld); after
          that many consecutive bad steps fit restores the last good
          checkpoint and raises a diagnostic error.

        Self-healing extensions (round 16, resilience.healing):

        * ``MXNET_SNAPSHOT_EVERY`` > 0 (with ``checkpoint=`` set)
          takes an async snapshot checkpoint every that many batches:
          the device→host copy happens at the step boundary, the
          atomic write on a background thread (``MXNET_CKPT_ASYNC=0``
          forces the write synchronous), so the recovery point is
          batches old instead of an epoch old at <5% step cost.
        * when peer healing is armed (``MXNET_HEARTBEAT_DIR`` + a
          multi-process elastic context, or an explicit
          ``healing.arm``), every step boundary renews this rank's
          heartbeat and polls the failure detector: a declared peer
          death fires the EMERGENCY checkpoint (freshest snapshot —
          no collective, the mesh is already broken) and raises
          ``PeerDeadError`` out of fit; the healing supervisor
          relaunches and the resume re-shards at the surviving world
          size (``auto_reshards`` counted).
        """
        assert num_epoch is not None, "please specify number of epochs"
        from .. import initializer as init_mod
        from ..config import get_env
        from ..resilience.checkpoint import (CheckpointManager,
                                             restore_rng)
        from ..resilience.preempt import PreemptionDrain

        if initializer is None:
            initializer = init_mod.Uniform(0.01)

        resume_state = None
        if resume_from is not None:
            rmgr = resume_from if isinstance(resume_from,
                                             CheckpointManager) \
                else CheckpointManager(str(resume_from))
            resume_state = rmgr.load()
            arg_params = resume_state["arg_params"]
            aux_params = resume_state["aux_params"]
            begin_epoch = int(resume_state["epoch"])
            force_init = True
            allow_missing = False
            self.logger.info(
                "Resuming fit from checkpoint epoch %d (batch cursor "
                "%d)", begin_epoch, resume_state["batch_cursor"])

        ckpt_mgr = None
        if checkpoint is not None:
            ckpt_mgr = checkpoint if isinstance(checkpoint,
                                                CheckpointManager) \
                else CheckpointManager(str(checkpoint),
                                       keep_n=get_env("MXNET_CKPT_KEEP"))

        self.bind(
            data_shapes=train_data.provide_data,
            label_shapes=train_data.provide_label,
            for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init)
        self.init_optimizer(
            kvstore=kvstore, optimizer=optimizer,
            optimizer_params=optimizer_params)

        resume_cursor = 0
        if resume_state is not None:
            resume_cursor = int(resume_state.get("batch_cursor", 0))
            # elastic resume: compare the checkpoint's topology stamp
            # with the live world BEFORE any state lands.  A changed
            # world size/bucket plan is a RESHARD (init_optimizer
            # already re-ran plan_buckets for the new shard count;
            # set_states below re-shards the gathered legacy pickle
            # onto it) — logged and counted, never a death.  A
            # same-topology resume is a verdict-level no-op.  The
            # batch cursor re-slices across the new data-mesh width
            # (global-batch units), raising only when the global
            # batch itself changed.
            old_topo = resume_state.get("topology")
            if old_topo:
                from .. import telemetry as _tm0
                from ..resilience import elastic as _elastic
                from ..resilience import healing as _healing0

                new_topo = self._topology_block()
                verdict = _elastic.reshard_verdict(old_topo, new_topo)
                resume_cursor = _elastic.reslice_cursor(
                    resume_cursor, old_topo, new_topo)
                if verdict["reshard"]:
                    self.logger.info(
                        "Elastic resume: topology changed (%s) — "
                        "re-planned buckets and re-sharding optimizer "
                        "state for the new world",
                        "; ".join(verdict["reasons"]))
                    _tm0.count("reshards")
                    _tm0.event("resize",
                               old_world=verdict["old_world"],
                               new_world=verdict["new_world"],
                               reasons=verdict["reasons"],
                               batch_cursor=resume_cursor)
                    if _healing0.relaunch_attempt() > 0:
                        # a supervisor relaunch healing a peer death:
                        # this reshard happened with NO operator
                        # action — count it apart from hand-driven
                        # resizes
                        _tm0.count("auto_reshards")
                        _tm0.heal("resume",
                                  old_world=verdict["old_world"],
                                  new_world=verdict["new_world"],
                                  batch_cursor=resume_cursor,
                                  attempt=_healing0.relaunch_attempt())
                else:
                    self.logger.info(
                        "Elastic resume: topology unchanged (world "
                        "%s) — no reshard", verdict["new_world"])
            states = resume_state.get("optimizer_states")
            if states:
                set_states = getattr(self, "_set_optimizer_states",
                                     None)
                if set_states is not None:
                    set_states(states)
            restore_rng(resume_state.get("rng"))

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        if resume_cursor > 0:
            # mid-epoch resume: skip the batches the interrupted run
            # already trained on, BEFORE the device-feed wrapper exists
            # — skipped batches must not pay host assembly + H2D just
            # to be discarded.  (The iterator must be deterministic for
            # bit-exact resume: same seed, same order.)
            skip_iter = iter(train_data)
            for _ in range(resume_cursor):
                try:
                    next(skip_iter)
                except StopIteration:
                    break

        # async device feed (MXNET_DEVICE_FEED, default on): host batch
        # assembly + the H2D transfer of the NEXT batch overlap the
        # running step; batches arrive device-committed (mesh-sharded
        # under a data mesh), so forward()'s own device_put is a no-op.
        # fit OWNS the wrapper it creates: it must be closed on the way
        # out or its producer keeps pulling from the caller's iterator
        # and races whatever consumes it next (predict/score).
        from ..io.device_feed import DeviceFeedIter, device_feed_enabled

        owned_feed = None
        if device_feed_enabled() and \
                not isinstance(train_data, DeviceFeedIter):
            train_data = owned_feed = DeviceFeedIter(
                train_data, mesh=getattr(self, "_mesh", None))
        # telemetry session (telemetry.fit_session is a no-op shell
        # when MXNET_RUNLOG is unset — the per-step fast exit): step
        # records, sampled loss syncs, and the crash flight dumps for
        # the in-fit death paths all hang off it
        from .. import telemetry as _tm

        batch_size = 0
        try:
            batch_size = int(train_data.provide_data[0][1][0])
        except Exception:
            pass
        # feed-wait/H2D deltas come from whichever DeviceFeedIter is
        # actually feeding the loop — fit's own wrapper or one the
        # caller wrapped themselves
        feed = owned_feed if owned_feed is not None else (
            train_data if isinstance(train_data, DeviceFeedIter)
            else None)
        session = _tm.fit_session(batch_size=batch_size, feed=feed)
        # peer healing (round 16): arm the heartbeat + failure
        # detector when the env configures them (MXNET_HEARTBEAT_DIR
        # with a multi-process world); unarmed this is one env read
        from ..resilience import healing as _healing

        _healing.arm_from_env()
        drain = PreemptionDrain()
        try:
            with drain:
                self._fit_epochs(
                    train_data, eval_data, eval_metric,
                    validation_metric, begin_epoch, num_epoch, monitor,
                    batch_end_callback, epoch_end_callback,
                    eval_end_callback, eval_batch_end_callback,
                    drain=drain, ckpt_mgr=ckpt_mgr,
                    checkpoint_period=checkpoint_period,
                    resume_cursor=resume_cursor, session=session)
            session.finish("preempted" if drain.requested is not None
                           else "ok")
        except BaseException as exc:  # noqa: BLE001 — flight-record
            # EVERY in-fit death (NaN-abort already dumped at its raise
            # site; re-dumping there is suppressed by reason tracking)
            session.flight(f"exception:{type(exc).__name__}")
            session.finish("error")
            raise
        finally:
            if ckpt_mgr is not None:
                # drain the async snapshot queue (every captured
                # snapshot lands or errors) and stop the writer; a
                # later fit/save on the same manager re-arms lazily
                try:
                    ckpt_mgr.close_async()
                except Exception:
                    pass
            if owned_feed is not None:
                owned_feed.close()
                # restore the caller's end-of-fit contract: the source
                # iterator comes back reset, not part-consumed by the
                # producer's final read-ahead
                if hasattr(owned_feed.base, "reset"):
                    owned_feed.base.reset()
        # drained: the final checkpoint is on disk and the feed is
        # closed — hand the signal back to its original disposition
        drain.reraise()

    def _fit_checkpoint_state(self, ckpt_mgr, epoch, batch_cursor):
        """(version, save kwargs) of the live module state — shared by
        the sync drain/boundary saves and the async snapshot cadence
        so the two flavors can never diverge in what they capture.

        Version ids are strictly monotonic (``allocate_version``
        accounts for queued-but-unwritten async snapshots too) — an
        existing version is NEVER rewritten in place, because
        per-version atomicity would not survive a crash landing
        between the params and manifest replaces of an in-place
        overwrite.  The manifest's epoch/batch_cursor fields carry the
        resume truth; the filename number is just a version id (it
        equals the epoch for clean uninterrupted runs, and shifts past
        it after a mid-epoch drain or between-save snapshots)."""
        arg_p, aux_p = self.get_params()
        states = None
        get_states = getattr(self, "_get_optimizer_states", None)
        if get_states is not None:
            try:
                states = get_states()
            except MXNetError:
                states = None  # optimizer not initialized yet
        version = ckpt_mgr.allocate_version(
            min_version=max(1, int(epoch)))
        # serialize the (constant) symbol once per module, not once
        # per cadence snapshot: tojson of a large graph on the step
        # boundary was the one capture cost left unmemoized
        cache = getattr(self, "_symbol_json_cache", None)
        if cache is None or cache[0] is not self._symbol:
            cache = (self._symbol, self._symbol.tojson()
                     if self._symbol is not None else None)
            self._symbol_json_cache = cache
        return version, dict(
            symbol_json=cache[1], arg_params=arg_p, aux_params=aux_p,
            optimizer_states=states, batch_cursor=batch_cursor,
            epoch=epoch, topology=self._topology_block())

    def _save_fit_checkpoint(self, ckpt_mgr, epoch, batch_cursor,
                             lock_timeout=None):
        """Flush one atomic checkpoint version synchronously (epoch
        boundaries, preemption drains).  ``lock_timeout`` bounds the
        writer-lock wait on the peer-death fallback path — when the
        async writer is wedged on a hung disk HOLDING the lock, the
        heal exit must proceed without it rather than join the
        deadlock."""
        version, kw = self._fit_checkpoint_state(ckpt_mgr, epoch,
                                                 batch_cursor)
        man = ckpt_mgr.save(version, lock_timeout=lock_timeout, **kw)
        if man is None and lock_timeout is not None:
            # the bounded wait expired (a wedged writer holds the
            # lock): NOT silent — the operator must know this drain/
            # heal exit left no fresh version behind
            self.logger.warning(
                "checkpoint version %d SKIPPED: writer lock still "
                "held after %.0fs (wedged async write?)", version,
                lock_timeout)
        return man

    def _snapshot_fit_checkpoint(self, ckpt_mgr, epoch, batch_cursor):
        """One MXNET_SNAPSHOT_EVERY cadence snapshot: capture at this
        step boundary, write off the critical path
        (``CheckpointManager.save_async``; ``MXNET_CKPT_ASYNC=0``
        forces the write synchronous for A/B and debugging).  The
        freshest capture doubles as the emergency-checkpoint source a
        peer death or watchdog abort flushes."""
        from ..config import get_env

        version, kw = self._fit_checkpoint_state(ckpt_mgr, epoch,
                                                 batch_cursor)
        if get_env("MXNET_CKPT_ASYNC"):
            ckpt_mgr.save_async(version, **kw)
        else:
            ckpt_mgr.save(version, **kw)

    def _topology_block(self):
        """The world stamp for this module's checkpoints
        (``resilience.elastic.topology_block``); subclasses with a
        mesh/sharded updater override with the real thing.  None keeps
        pre-elastic manifests byte-compatible."""
        return None

    def _emit_tensor_stats(self, step, epoch, bad_step):
        """Numerics-monitor emission for the eager executor path: one
        jitted summary pass over the named gradient buffers, recorded
        as a ``tensor_stats`` run-log record.  Only ever called on
        sampled or bad steps; never lets a telemetry failure kill
        training."""
        from .. import telemetry as _tm
        from ..telemetry import numerics as _nm

        rl = _tm.current()
        grads_of = getattr(self, "_named_grads", None)
        if rl is None or grads_of is None:
            return
        try:
            grads = grads_of()
            if not grads:
                return
            vecs = _nm.summarize_named(grads)
            _nm.emit(rl, step, vecs, where="grad", epoch=epoch)
        except Exception:
            self.logger.debug("numerics monitor emission failed",
                              exc_info=True)

    def _outputs_finite(self):
        """NaN/Inf probe over the step's outputs (forces a device
        sync — only ever called with the bad-step guard armed)."""
        for out in self.get_outputs():
            a = out.asnumpy() if hasattr(out, "asnumpy") \
                else onp.asarray(out)
            if not onp.isfinite(a).all():
                return False
        return True

    def _step_finite(self):
        """Whether the step just run is safe to apply.  Subclasses
        with gradient access (Module) extend this to probe the grads
        too — finite outputs with a non-finite gradient (log(0) in the
        loss backward, bf16 overflow in backprop) would otherwise
        slip a poisoned update through the guard."""
        return self._outputs_finite()

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, begin_epoch, num_epoch, monitor,
                    batch_end_callback, epoch_end_callback,
                    eval_end_callback, eval_batch_end_callback,
                    drain=None, ckpt_mgr=None, checkpoint_period=1,
                    resume_cursor=0, session=None):
        from ..config import get_env
        from ..resilience import faultsim
        from ..resilience import healing as _healing
        from ..telemetry import numerics as _nm

        if session is None:  # direct callers (tests) get the shell —
            # runlog-less AND watchdog-less: fit() owns the armed
            # session and finish()es it; nothing on this path would
            # ever close an auto-armed watchdog thread, so it must
            # not exist (a leaked one fires bogus stall dumps after
            # the short fit returns)
            from ..telemetry.session import FitSession

            session = FitSession(None, watchdog=False)

        bad_limit = int(get_env("MXNET_BAD_STEP_LIMIT"))
        bad_run = 0

        # peer healing (round 16): with a detector armed, the
        # collective-bearing calls run under guard_collective so a
        # peer dying MID-collective surfaces as PeerDeadError instead
        # of wedging the survivor until the watchdog; unarmed, this
        # is a plain call (one dict lookup)
        def _guarded(fn, label):
            det = _healing.detector()
            if det is None:
                return fn()
            return _healing.guard_collective(fn, det, label=label)

        def _heal_out(epoch, nbatch):
            # the emergency checkpoint flushes the freshest snapshot
            # (no collective — the mesh is already broken); with no
            # snapshot captured yet, fall back to a direct save (the
            # eager Module's state is process-local)
            paths = _healing.fire_emergency("peer_death")
            if not paths and ckpt_mgr is not None:
                try:
                    # bounded lock wait: if the emergency flush gave
                    # up because a wedged writer holds _write_lock,
                    # this fallback must not block on it forever —
                    # heal_exit matters more than one more version
                    self._save_fit_checkpoint(ckpt_mgr, epoch, nbatch,
                                              lock_timeout=10.0)
                except Exception:
                    self.logger.exception(
                        "peer-death fallback checkpoint failed")

        # async snapshot cadence (round 16): every N batches, capture
        # params/opt-state/RNG/cursor at the step boundary and write
        # off the critical path — the recovery point a peer death or
        # watchdog abort flushes is batches old, not an epoch old
        snap_every = int(get_env("MXNET_SNAPSHOT_EVERY")) \
            if ckpt_mgr is not None else 0
        snap_step = 0
        # numerics monitor (MXNET_NUMERICS), eager executor flavour:
        # the gradients are host-visible arrays here, so the jitted
        # summaries run ONLY on sampled steps and on every bad step —
        # off-sample the monitor costs nothing at all
        numerics_on = _nm.armed()
        nm_period = _nm.sample_period() if numerics_on else 0
        nm_step = 0
        checkpoint_period = int(max(1, checkpoint_period))
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            if epoch == begin_epoch and resume_cursor > 0:
                # fit() already skipped the source ahead (pre-wrap);
                # only the batch numbering resumes here
                nbatch = resume_cursor
            end_of_batch = False
            boundary_resume = False
            next_data_batch = None
            try:
                next_data_batch = next(data_iter)
            except StopIteration:
                if epoch == begin_epoch and resume_cursor > 0:
                    # resume landed exactly on the epoch boundary:
                    # nothing left to train, but the epoch-end contract
                    # below (callbacks, boundary checkpoint, eval)
                    # still runs so the checkpoint cadence matches an
                    # uninterrupted run
                    boundary_resume = True
                else:
                    # a genuinely empty iterator stays the loud failure
                    # it always was, not a silent no-op training run
                    raise
            drained = False
            while not end_of_batch and not boundary_resume:
                data_batch = next_data_batch
                if monitor is not None:
                    monitor.tic()
                session.step_begin()
                try:
                    _guarded(lambda: self.forward_backward(data_batch),
                             "fit_forward_backward")
                except _healing.PeerDeadError:
                    _heal_out(epoch, nbatch)
                    raise
                bad_step = False
                if bad_limit > 0:
                    bad_step = (faultsim.inject("step.loss_nan")
                                == "nan") or not self._step_finite()
                if numerics_on and (bad_step
                                    or nm_step % nm_period == 0):
                    self._emit_tensor_stats(nm_step, epoch, bad_step)
                nm_step += 1
                if bad_step:
                    # skip-and-count, like dynamic loss scaling: the
                    # update is withheld so one NaN batch cannot poison
                    # the params
                    bad_run += 1
                    self.logger.warning(
                        "Epoch[%d] Batch[%d] non-finite step — update "
                        "skipped (%d/%d consecutive)", epoch, nbatch,
                        bad_run, bad_limit)
                    if bad_run >= bad_limit:
                        restored = None
                        if ckpt_mgr is not None:
                            restored = ckpt_mgr.latest_epoch()
                            if restored is not None:
                                # full rollback, not just weights: a
                                # caller that catches and resumes must
                                # not pair rolled-back params with
                                # post-divergence optimizer moments
                                from ..resilience.checkpoint import \
                                    restore_rng as _restore_rng

                                state = ckpt_mgr.load(restored)
                                self.set_params(state["arg_params"],
                                                state["aux_params"])
                                set_states = getattr(
                                    self, "_set_optimizer_states",
                                    None)
                                if set_states is not None and \
                                        state.get("optimizer_states"):
                                    set_states(
                                        state["optimizer_states"])
                                _restore_rng(state.get("rng"))
                        session.flight("nan_abort")
                        raise MXNetError(
                            f"aborting fit: {bad_run} consecutive "
                            f"non-finite steps (MXNET_BAD_STEP_LIMIT="
                            f"{bad_limit}) at epoch {epoch} batch "
                            f"{nbatch}; parameters "
                            + (f"restored to checkpoint epoch "
                               f"{restored}" if restored is not None
                               else "left as of the last finite step "
                               "(no checkpoint to restore)"))
                else:
                    bad_run = 0
                    try:
                        _guarded(self.update, "fit_update")
                    except _healing.PeerDeadError:
                        _heal_out(epoch, nbatch)
                        raise
                try:
                    next_data_batch = next(data_iter)
                except StopIteration:
                    end_of_batch = True
                self.update_metric(eval_metric, data_batch.label)
                if session:
                    # sampled device sync only: unsampled steps keep
                    # wall timing but read no metric value
                    synced = session.should_sync()
                    loss_val = None
                    if synced:
                        try:
                            nv = eval_metric.get_name_value()
                            if nv and nv[0][1] == nv[0][1]:  # not NaN
                                loss_val = float(nv[0][1])
                        except Exception:
                            pass
                    session.step_end(epoch, nbatch, loss=loss_val,
                                     synced=synced, bad_step=bad_step)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(_BatchEndParam(epoch, nbatch, eval_metric))
                nbatch += 1
                snap_step += 1
                # peer healing poll (one dict lookup unarmed): renew
                # this rank's beat and raise PeerDeadError on a
                # declared death — the emergency checkpoint flushes
                # from the freshest snapshot (no collective: the mesh
                # is already broken), then fit unwinds with the flight
                # dump and the supervisor owns the relaunch.  The poll
                # runs BEFORE the cadence snapshot: the snapshot's
                # device→host gather is itself a collective on a
                # mesh-backed module, and it must not start against a
                # peer that died during the previous step
                try:
                    _healing.poll(step=snap_step)
                except _healing.PeerDeadError:
                    _heal_out(epoch, nbatch)
                    raise
                if snap_every > 0 and snap_step % snap_every == 0:
                    try:
                        _guarded(lambda: self._snapshot_fit_checkpoint(
                            ckpt_mgr, epoch, nbatch), "fit_snapshot")
                    except _healing.PeerDeadError:
                        _heal_out(epoch, nbatch)
                        raise
                if drain is not None and drain.requested is not None:
                    # preemption drain: the in-flight step is done —
                    # flush a final checkpoint with the batch cursor,
                    # then unwind (fit closes the feed and re-raises).
                    # Queued async snapshots land first (wait_async)
                    # so the drain save is the newest version
                    drained_ckpt = None
                    if ckpt_mgr is not None:
                        ckpt_mgr.wait_async(timeout=30.0)
                        # bounded lock wait, like the peer-death
                        # fallback: a writer wedged PAST wait_async's
                        # budget still holds _write_lock, and the
                        # drain must exit rc -15 before the external
                        # kill -9 rather than join the deadlock
                        drained_ckpt = self._save_fit_checkpoint(
                            ckpt_mgr, epoch, nbatch,
                            lock_timeout=15.0)
                    self.logger.info(
                        "Preemption drain (signal %s): %s at epoch "
                        "%d batch %d", drain.requested,
                        "checkpoint" if drained_ckpt is not None
                        else "NO checkpoint written", epoch, nbatch)
                    # post-mortem of the preempted run: the last N
                    # step records land beside the drain checkpoint
                    session.flight("preempt_drain")
                    drained = True
                    break
            if drained:
                return
            if not boundary_resume:
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch,
                                     name, val)
                toc = time.time()
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                 toc - tic)

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for callback in _as_list(epoch_end_callback):
                    callback(epoch, self.symbol, arg_p, aux_p)
            if ckpt_mgr is not None \
                    and (epoch + 1) % checkpoint_period == 0:
                # epoch boundary: cursor 0, epoch field = next epoch.
                # The schedule is ABSOLUTE (epoch number, not epochs
                # since begin_epoch), so a resume keeps the
                # uninterrupted run's checkpoint cadence.
                self._save_fit_checkpoint(ckpt_mgr, epoch + 1, 0)

            if eval_data is not None:
                res = self.score(
                    eval_data, validation_metric,
                    score_end_callback=eval_end_callback,
                    batch_end_callback=eval_batch_end_callback,
                    epoch=epoch)
                for name, val in res:
                    self.logger.info(
                        "Epoch[%d] Validation-%s=%f", epoch, name, val)
            train_data.reset()

    # subclass responsibilities ----------------------------------------
    def bind(self, *a, **k):
        raise NotImplementedError

    def init_params(self, *a, **k):
        raise NotImplementedError

    def init_optimizer(self, *a, **k):
        raise NotImplementedError

    def forward(self, *a, **k):
        raise NotImplementedError

    def backward(self, *a, **k):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, *a, **k):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(
            initializer=None, arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init,
            allow_extra=allow_extra)

    def install_monitor(self, mon):
        raise NotImplementedError


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = None


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
