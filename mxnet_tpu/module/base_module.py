"""BaseModule: the high-level train/predict interface.

Reference parity: python/mxnet/module/base_module.py (``fit`` :409-538 —
bind → init_params → init_optimizer → epoch loop forward_backward /
update / metric / checkpoint; ``score``, ``predict``).
"""
from __future__ import annotations

import logging
import time

import numpy as onp

from .. import metric as metric_mod
from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ------------------------------------------------------ infra props
    @property
    def symbol(self):
        return self._symbol

    def _check_binded(self):
        if not self.binded:
            raise MXNetError("Module not binded")

    # ------------------------------------------------------ train loop
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(_BatchEndParam(epoch, nbatch, eval_metric))
            actual_num_batch += 1
        if score_end_callback:
            for cb in _as_list(score_end_callback):
                cb(_BatchEndParam(epoch, actual_num_batch, eval_metric))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [
                out[0 : out.shape[0] - (pad or 0)]
                for out in self.get_outputs()
            ]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise MXNetError(
                        "Cannot merge batches: different number of outputs")
            output_list2 = [
                nd.concat(*[out[i] for out in output_list], dim=0)
                for i in range(num_outputs)
            ]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """Full training loop (reference base_module.py:409-538)."""
        assert num_epoch is not None, "please specify number of epochs"
        from .. import initializer as init_mod

        if initializer is None:
            initializer = init_mod.Uniform(0.01)

        self.bind(
            data_shapes=train_data.provide_data,
            label_shapes=train_data.provide_label,
            for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init)
        self.init_optimizer(
            kvstore=kvstore, optimizer=optimizer,
            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        # async device feed (MXNET_DEVICE_FEED, default on): host batch
        # assembly + the H2D transfer of the NEXT batch overlap the
        # running step; batches arrive device-committed (mesh-sharded
        # under a data mesh), so forward()'s own device_put is a no-op.
        # fit OWNS the wrapper it creates: it must be closed on the way
        # out or its producer keeps pulling from the caller's iterator
        # and races whatever consumes it next (predict/score).
        from ..io.device_feed import DeviceFeedIter, device_feed_enabled

        owned_feed = None
        if device_feed_enabled() and \
                not isinstance(train_data, DeviceFeedIter):
            train_data = owned_feed = DeviceFeedIter(
                train_data, mesh=getattr(self, "_mesh", None))
        try:
            self._fit_epochs(
                train_data, eval_data, eval_metric, validation_metric,
                begin_epoch, num_epoch, monitor, batch_end_callback,
                epoch_end_callback, eval_end_callback,
                eval_batch_end_callback)
        finally:
            if owned_feed is not None:
                owned_feed.close()
                # restore the caller's end-of-fit contract: the source
                # iterator comes back reset, not part-consumed by the
                # producer's final read-ahead
                if hasattr(owned_feed.base, "reset"):
                    owned_feed.base.reset()

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, begin_epoch, num_epoch, monitor,
                    batch_end_callback, epoch_end_callback,
                    eval_end_callback, eval_batch_end_callback):
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                try:
                    next_data_batch = next(data_iter)
                except StopIteration:
                    end_of_batch = True
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(_BatchEndParam(epoch, nbatch, eval_metric))
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, toc - tic)

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for callback in _as_list(epoch_end_callback):
                    callback(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(
                    eval_data, validation_metric,
                    score_end_callback=eval_end_callback,
                    batch_end_callback=eval_batch_end_callback,
                    epoch=epoch)
                for name, val in res:
                    self.logger.info(
                        "Epoch[%d] Validation-%s=%f", epoch, name, val)
            train_data.reset()

    # subclass responsibilities ----------------------------------------
    def bind(self, *a, **k):
        raise NotImplementedError

    def init_params(self, *a, **k):
        raise NotImplementedError

    def init_optimizer(self, *a, **k):
        raise NotImplementedError

    def forward(self, *a, **k):
        raise NotImplementedError

    def backward(self, *a, **k):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, *a, **k):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(
            initializer=None, arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init,
            allow_extra=allow_extra)

    def install_monitor(self, mon):
        raise NotImplementedError


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = None


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
