"""BucketingModule: per-sequence-length modules sharing parameters.

Reference parity: python/mxnet/module/bucketing_module.py (702 LoC) —
per-bucket executors sharing one memory pool; the TPU-native analog is
per-bucket jit cache entries sharing the same parameter arrays (XLA owns
memory).  SURVEY.md §5.7: bucketing is the reference's variable-length
strategy.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(
            sym, data_names, label_names, logger=self.logger,
            context=self._context,
            fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self.for_training,
                        force_rebind=False,
                        shared_module=self._buckets[
                            self._default_bucket_key],
                        grad_req="write")
            if self._curr_module is not None and \
                    self._curr_module.optimizer_initialized:
                module._optimizer = self._curr_module._optimizer
                module._updater = self._curr_module._updater
                module.optimizer_initialized = True
            self._buckets[bucket_key] = module
        else:
            module = self._buckets[bucket_key]
        self._curr_module = module
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._check_binded()
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def get_params(self):
        self._check_binded()
        arg, aux = self._curr_module.get_params()
        self._params_dirty = False
        return arg, aux

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._check_binded()
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(
            kvstore, optimizer, optimizer_params, force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        self._check_binded()
        bucket_key = getattr(data_batch, "bucket_key", None)
        if bucket_key is None:
            bucket_key = self._default_bucket_key
        data_shapes = [(getattr(d, "name", f"data{i}")
                        if not isinstance(d, tuple) else d[0],
                        tuple(a.shape))
                       for i, (d, a) in enumerate(
                           zip(data_batch.provide_data or
                               [("data", None)] * len(data_batch.data),
                               data_batch.data))]
        label_shapes = None
        if data_batch.label:
            provide = (data_batch.provide_label
                       or [("softmax_label", None)] * len(data_batch.label))
            label_shapes = [
                (getattr(d, "name", None) if not isinstance(d, tuple)
                 else d[0], tuple(a.shape))
                for d, a in zip(provide, data_batch.label)]
        self.switch_bucket(bucket_key, data_shapes, label_shapes)
        # params shared by reference: sync from previous module
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._check_binded()
        self._curr_module.backward(out_grads=out_grads)
        self._params_dirty = True

    def update(self):
        self._check_binded()
        assert self.optimizer_initialized
        self._params_dirty = True
        # parameter NDArrays are shared across buckets (Module.bind
        # shared_module) — one update is visible everywhere
        self._curr_module.update()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._check_binded()
        self._curr_module.update_metric(eval_metric, labels)

    def get_outputs(self, merge_multi_context=True):
        self._check_binded()
        return self._curr_module.get_outputs(merge_multi_context)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)
