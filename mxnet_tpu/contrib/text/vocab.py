"""Vocabulary (reference contrib/text/vocab.py Vocabulary)."""
from __future__ import annotations

__all__ = ["Vocabulary"]


class Vocabulary:
    """Indexes tokens by frequency (reference Vocabulary contract:
    index 0 is the unknown token; reserved tokens follow; then tokens
    by descending frequency, ties broken lexically)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            if unknown_token in reserved_tokens:
                raise ValueError(
                    "unknown_token must not appear in reserved_tokens")
            if len(set(reserved_tokens)) != len(reserved_tokens):
                raise ValueError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens)
                                 if reserved_tokens else None)
        self._idx_to_token = [unknown_token] + (self._reserved_tokens
                                                or [])
        self._token_to_idx = {t: i
                              for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        room = (most_freq_count if most_freq_count is not None
                else len(pairs))
        for token, freq in pairs:
            if freq < min_freq or room <= 0:
                break
            if token in self._token_to_idx:
                continue
            self._token_to_idx[token] = len(self._idx_to_token)
            self._idx_to_token.append(token)
            room -= 1

    def __len__(self):
        return len(self._idx_to_token)

    def __contains__(self, token):
        return token in self._token_to_idx

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index/indices; unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"token index {i} out of range")
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks
