"""Text token-counting helpers (reference contrib/text/utils.py)."""
from __future__ import annotations

import re
from collections import Counter

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Tokenize ``source_str`` on the delimiters and count tokens
    (reference utils.py count_tokens_from_str)."""
    source_str = re.split(token_delim + "|" + seq_delim, source_str)
    tokens = [t for t in source_str if t]
    if to_lower:
        tokens = [t.lower() for t in tokens]
    if counter_to_update is None:
        return Counter(tokens)
    counter_to_update.update(tokens)
    return counter_to_update
