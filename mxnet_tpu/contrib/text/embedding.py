"""Token embeddings (reference contrib/text/embedding.py).

``register``/``create`` mirror the reference's registry.  The reference
downloads GloVe/FastText archives on demand; this environment has no
egress, so the pretrained classes load from a LOCAL file path passed as
``pretrained_file_name`` (the same text format: one token per line,
token then vector values, whitespace-separated).  ``CustomEmbedding``
is byte-for-byte the reference behavior.
"""
from __future__ import annotations

import io
import logging

import numpy as onp

from ... import ndarray as nd
from ...base import MXNetError
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "GloVe", "FastText"]

_REGISTRY = {}


def register(cls):
    """Register a TokenEmbedding subclass under its lowercase name."""
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding (reference embedding.create)."""
    key = embedding_name.lower()
    if key not in _REGISTRY:
        raise MXNetError(
            f"unknown embedding {embedding_name!r}; registered: "
            f"{sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Names of the pretrained files each embedding understands.  With
    no egress these are documentation — pass a local file instead."""
    table = {n: list(c.pretrained_file_names)
             for n, c in _REGISTRY.items()}
    if embedding_name is None:
        return table
    return table.get(embedding_name.lower(), [])


class TokenEmbedding:
    """Base: token -> vector store with vocabulary indexing
    (reference TokenEmbedding)."""

    pretrained_file_names = ()

    def __init__(self, unknown_token="<unk>"):
        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec = None  # nd.NDArray (n, dim)

    # ------------------------------------------------------------- load
    def _load_embedding_txt(self, file_path, elem_delim=" ",
                            encoding="utf8"):
        vecs = []
        dim = None
        with io.open(file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) <= 2:
                    continue  # header or malformed line
                token, elems = parts[0], parts[1:]
                if dim is None:
                    dim = len(elems)
                    vecs.append(onp.zeros(dim, "float32"))  # <unk> row
                if len(elems) != dim:
                    logging.warning(
                        "line %d of %s has %d values, expected %d — "
                        "skipped", line_num, file_path, len(elems), dim)
                    continue
                if token in self._token_to_idx:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(onp.asarray(elems, "float32"))
        if dim is None:
            raise MXNetError(f"no embedding vectors found in {file_path}")
        self._idx_to_vec = nd.array(onp.stack(vecs))

    # ------------------------------------------------------------ query
    @property
    def vec_len(self):
        return int(self._idx_to_vec.shape[1])

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def __len__(self):
        return len(self._idx_to_token)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idx.append(0 if i is None else i)
        rows = self._idx_to_vec._data[onp.asarray(idx)]
        out = nd.NDArray(rows)
        return nd.NDArray(out._data[0]) if single else out

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        mat = new_vectors._data if isinstance(new_vectors, nd.NDArray) \
            else onp.asarray(new_vectors)
        if mat.ndim == 1:
            mat = mat[None, :]
        idx = []
        for t in toks:
            if t not in self._token_to_idx:
                raise MXNetError(
                    f"token {t!r} is unknown; only known-token vectors "
                    "can be updated")
            idx.append(self._token_to_idx[t])
        data = self._idx_to_vec._data
        self._idx_to_vec._adopt(
            data.at[onp.asarray(idx)].set(mat.astype(data.dtype)))


@register
class GloVe(TokenEmbedding):
    """GloVe vectors from a LOCAL glove.*.txt file (the reference
    downloads from the stanford archive — no egress here)."""

    pretrained_file_names = (
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt")

    def __init__(self, pretrained_file_name="glove.6B.50d.txt",
                 embedding_root=None, unknown_token="<unk>", **kwargs):
        super().__init__(unknown_token=unknown_token)
        import os

        path = pretrained_file_name if embedding_root is None else \
            os.path.join(embedding_root, pretrained_file_name)
        if not os.path.exists(path):
            raise MXNetError(
                f"{path} not found; downloads are unavailable in this "
                "environment — place the GloVe txt file locally and "
                "pass its path")
        self._load_embedding_txt(path)


@register
class FastText(TokenEmbedding):
    """FastText .vec vectors from a LOCAL file (same txt format, with a
    count/dim header line that the loader skips)."""

    pretrained_file_names = (
        "wiki.simple.vec", "wiki.en.vec", "crawl-300d-2M.vec")

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=None, unknown_token="<unk>", **kwargs):
        super().__init__(unknown_token=unknown_token)
        import os

        path = pretrained_file_name if embedding_root is None else \
            os.path.join(embedding_root, pretrained_file_name)
        if not os.path.exists(path):
            raise MXNetError(
                f"{path} not found; downloads are unavailable in this "
                "environment — place the .vec file locally and pass "
                "its path")
        self._load_embedding_txt(path)


class CustomEmbedding(TokenEmbedding):
    """User-provided embedding file (reference CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", unknown_token="<unk>", **kwargs):
        super().__init__(unknown_token=unknown_token)
        self._load_embedding_txt(pretrained_file_path, elem_delim,
                                 encoding)


class CompositeEmbedding(TokenEmbedding):
    """Concatenation of several embeddings over one vocabulary
    (reference CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(vocabulary, Vocabulary):
            raise MXNetError("vocabulary must be a text.Vocabulary")
        if isinstance(token_embeddings, TokenEmbedding):
            token_embeddings = [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._vocabulary = vocabulary
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        blocks = []
        for emb in token_embeddings:
            vecs = emb.get_vecs_by_tokens(self._idx_to_token)
            blocks.append(vecs._data)
        import jax.numpy as jnp

        self._idx_to_vec = nd.NDArray(jnp.concatenate(blocks, axis=1))

    @property
    def vocabulary(self):
        return self._vocabulary
