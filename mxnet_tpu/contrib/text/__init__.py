"""Text utilities (reference python/mxnet/contrib/text/): vocabulary,
token embeddings, composite embeddings."""
from . import embedding, utils, vocab  # noqa: F401
from .vocab import Vocabulary  # noqa: F401

__all__ = ["embedding", "utils", "vocab", "Vocabulary"]
