"""TensorBoard logging callback (reference
python/mxnet/contrib/tensorboard.py LogMetricsCallback).

The reference delegates to the external ``tensorboard`` python package;
this environment has none, so the event-file writer is implemented
here: standard TFRecord framing (length + masked crc32c) around
hand-encoded Event/Summary protobuf messages — only the scalar-summary
subset TensorBoard needs.  Files written here load in stock
TensorBoard.
"""
from __future__ import annotations

import os
import struct
import time

__all__ = ["SummaryWriter", "LogMetricsCallback"]


# ------------------------------------------------------------- crc32c
_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data):
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------- minimal proto encode
def _varint(n):
    if n < 0:
        # protobuf encodes negative ints as 64-bit two's complement
        # (10 bytes); python's arithmetic shift would loop forever
        n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field(num, wire, payload):
    return _varint((num << 3) | wire) + payload


def _f_double(num, v):
    return _field(num, 1, struct.pack("<d", v))


def _f_float(num, v):
    return _field(num, 5, struct.pack("<f", v))


def _f_varint(num, v):
    return _field(num, 0, _varint(v))


def _f_bytes(num, data):
    return _field(num, 2, _varint(len(data)) + data)


def _scalar_event(tag, value, step, wall_time):
    # Summary.Value { tag = 1; simple_value = 2 }
    sval = _f_bytes(1, tag.encode()) + _f_float(2, float(value))
    summary = _f_bytes(1, sval)                  # Summary.value = 1
    # Event { wall_time = 1; step = 2; summary = 5 }
    return (_f_double(1, wall_time) + _f_varint(2, int(step))
            + _f_bytes(5, summary))


class SummaryWriter:
    """Minimal events-file writer: ``add_scalar(tag, value, step)``."""

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.mxnet_tpu"
        self._f = open(os.path.join(logdir, fname), "wb")
        # first record: file-version event
        self._write(_f_double(1, time.time())
                    + _f_bytes(3, b"brain.Event:2"))

    def _write(self, event_bytes):
        header = struct.pack("<Q", len(event_bytes))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(event_bytes)
        self._f.write(struct.pack("<I", _masked_crc(event_bytes)))

    def add_scalar(self, tag, value, step=0, wall_time=None):
        self._write(_scalar_event(
            tag, value, step, time.time() if wall_time is None
            else wall_time))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


class LogMetricsCallback:
    """Epoch/batch-end callback that logs every metric to TensorBoard
    (reference contrib/tensorboard.py surface: ``prefix`` namespaces
    the tags)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        self.step += 1
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)
        self.summary_writer.flush()
