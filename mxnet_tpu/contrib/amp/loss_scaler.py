"""Dynamic loss scaler (reference: python/mxnet/contrib/amp/loss_scaler.py).

Scale doubles after ``scale_window`` consecutive overflow-free steps and
halves on overflow; overflow detection uses the ``multi_all_finite`` op
(reference src/operator/contrib/all_finite.cc).
"""
from __future__ import annotations

import logging


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True when any gradient of ``params`` is non-finite."""
        from ... import ndarray as nd

        grads = [p._data._grad for p in params
                 if p._data is not None and p._data._grad is not None]
        if not grads:
            return False
        ok = nd.invoke("multi_all_finite", grads, num_arrays=len(grads))
        return float(ok.asnumpy()[0]) == 0.0

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
            logging.info("AMP: gradient overflow, lowering loss scale to "
                         "%g", self.loss_scale)
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
