"""AMP op-classification lists — policy as data.

Reference parity: python/mxnet/contrib/amp/lists/symbol.py, which
classifies every operator into FP16_FUNCS (run in the low-precision
target dtype), FP32_FUNCS (numerically sensitive, keep fp32),
FP16_FP32_FUNCS (run in whatever dtype the input already has) and
WIDEST_TYPE_CASTS (multi-input ops whose inputs are cast to the widest
present dtype).  Names refer to this framework's op registry; ops not
listed default to pass-through (the reference's FP16_FP32 class).
"""

# MXU-heavy ops: cast inputs to the AMP target dtype (bf16 on TPU)
TARGET_DTYPE_OPS = [
    "Convolution", "Convolution_v1", "Deconvolution", "FullyConnected",
    "dot", "batch_dot", "RNN", "_linalg_gemm", "_linalg_gemm2",
    "_npi_matmul",
]

# numerically sensitive ops: force fp32 inputs
FP32_OPS = [
    "softmax", "log_softmax", "softmin", "SoftmaxActivation",
    "SoftmaxOutput", "softmax_cross_entropy", "CTCLoss", "ctc_loss",
    "BatchNorm", "BatchNorm_v1", "LayerNorm", "GroupNorm", "InstanceNorm",
    "L2Normalization", "LRN", "norm", "exp", "log", "log2", "log10",
    "log1p", "expm1", "rsqrt", "rcbrt", "reciprocal", "erfinv", "gamma",
    "gammaln", "sum", "mean", "prod", "nansum", "nanprod",
    "LinearRegressionOutput", "MAERegressionOutput",
    "LogisticRegressionOutput", "SVMOutput", "smooth_l1", "MakeLoss",
    "make_loss",
]

# multi-input ops: cast every floating input to the widest input dtype
WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_power", "broadcast_maximum", "broadcast_minimum",
    "broadcast_hypot", "broadcast_equal", "broadcast_not_equal",
    "broadcast_greater", "broadcast_greater_equal", "broadcast_lesser",
    "broadcast_lesser_equal", "elemwise_add", "elemwise_sub",
    "elemwise_mul", "elemwise_div", "maximum", "minimum", "hypot",
    "power", "Concat", "concat", "stack", "add_n", "where",
]

# fp8-eligible ops (round 19): ONLY the MXU matmul/conv family — the
# same eligibility rule the dtype ladder's fp8 rung applies in
# make_train_step (weights of ndim >= 2 feeding matmul/conv get the
# e4m3 qdq; norms, softmax and reductions never drop below bf16, so
# every FP32_OPS entry stays out by construction).  A strict subset of
# TARGET_DTYPE_OPS: RNN gates and the linalg kernels carry recurrences
# / long accumulation chains that e4m3's ~2 significant digits cannot
# hold, so they cap at the bf16 rung.
FP8_OPS = [
    "Convolution", "Convolution_v1", "Deconvolution", "FullyConnected",
    "dot", "batch_dot", "_npi_matmul",
]

# reference-compat aliases
FP16_FUNCS = TARGET_DTYPE_OPS
FP32_FUNCS = FP32_OPS
FP8_FUNCS = FP8_OPS
