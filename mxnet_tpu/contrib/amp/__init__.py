"""Automatic Mixed Precision (reference: python/mxnet/contrib/amp/amp.py).

TPU-native design: the reference rewrites the symbol graph, inserting
``amp_cast``/``amp_multicast`` nodes per its op lists
(src/nnvm/low_precision_pass.cc).  Here the same policy lists drive the
SINGLE eager dispatch point (ndarray.invoke): when AMP is active,
floating inputs of MXU-heavy ops are cast to the target dtype, fp32-list
ops get fp32 inputs, and widest-cast ops promote to the widest input
dtype.  XLA fuses the resulting converts, which is exactly what the
reference's graph pass painstakingly arranges by hand.

Loss scaling: ``init_trainer`` + ``scale_loss`` give gluon training
dynamic loss scaling with overflow skipping (all_finite op); the fused
SPMD path has the same logic compiled in via
``make_train_step(loss_scale='dynamic')``.
"""
from __future__ import annotations

import contextlib
import functools

import jax.numpy as jnp

from ...base import MXNetError
from . import lists
from .loss_scaler import LossScaler

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "lists", "LossScaler"]

_active = False
_target_dtype = None
_target_set = frozenset()
_fp32_set = frozenset()
_widest_set = frozenset()


def init(target_dtype="bfloat16"):
    """Turn on AMP for eager/gluon execution (reference amp.py:init).

    The reference only allows calling once; re-init with a different
    dtype raises, matching that behavior.
    """
    global _active, _target_dtype, _target_set, _fp32_set, _widest_set
    if isinstance(target_dtype, str):
        if target_dtype in ("bfloat16", "bf16"):
            target_dtype = jnp.bfloat16
        elif target_dtype in ("float16", "fp16"):
            target_dtype = jnp.float16
        else:
            raise MXNetError(
                f"AMP target_dtype must be bfloat16 or float16, got "
                f"{target_dtype!r}")
    if _active and target_dtype != _target_dtype:
        raise MXNetError("AMP already initialized with a different dtype")
    _target_dtype = target_dtype
    _target_set = frozenset(lists.TARGET_DTYPE_OPS)
    _fp32_set = frozenset(lists.FP32_OPS)
    _widest_set = frozenset(lists.WIDEST_TYPE_CASTS)
    _active = True


def is_active():
    return _active


def _off():
    """Internal/test helper: disable AMP."""
    global _active
    _active = False


def _is_float(a):
    return jnp.issubdtype(a.dtype, jnp.floating)


def cast_inputs(op_name, arrays):
    """Apply the policy lists to one op invocation's array inputs."""
    if op_name in _target_set:
        return [a.astype(_target_dtype) if _is_float(a) else a
                for a in arrays]
    if op_name in _fp32_set:
        return [a.astype(jnp.float32) if _is_float(a) and
                a.dtype != jnp.float32 else a for a in arrays]
    if op_name in _widest_set:
        floats = [a.dtype for a in arrays if _is_float(a)]
        if len(set(floats)) > 1:
            widest = functools.reduce(jnp.promote_types, floats)
            return [a.astype(widest) if _is_float(a) else a
                    for a in arrays]
    return arrays


def init_trainer(trainer):
    """Attach a dynamic LossScaler to a gluon Trainer (reference
    amp.py:init_trainer)."""
    if getattr(trainer, "_amp_loss_scaler", None) is None:
        trainer._amp_loss_scaler = LossScaler()
        trainer._amp_original_scale = trainer._scale
    return trainer


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Scale the loss and arrange for gradients to be unscaled in
    trainer.step (reference amp.py:scale_loss)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) first")
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Divide current gradients by the loss scale (for gradient clipping
    between backward and step; reference amp.py:unscale)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) first")
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        g = p._data._grad if p._data is not None else None
        if g is not None:
            g._adopt(g._data * inv)
    trainer._scale = trainer._amp_original_scale


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16"):
    """Convert a symbolic model for low-precision inference (reference
    amp.py:convert_model).

    The reference inserts amp_cast nodes into the graph; on TPU the
    dispatch-level policy handles activation dtypes, so converting a
    model = casting its parameters (norm stats stay fp32).
    """
    from ...parallel import amp_cast_params

    dt = jnp.bfloat16 if str(target_dtype) in ("bfloat16", "bf16") \
        else jnp.float16
    arg_np = {k: v._data for k, v in arg_params.items()}
    aux_keep = dict(aux_params)  # aux = norm running stats: keep fp32
    casted = amp_cast_params(arg_np, dt)
    from ... import ndarray as nd

    return sym, {k: nd.NDArray(v) for k, v in casted.items()}, aux_keep


def convert_hybrid_block(block, target_dtype="bfloat16"):
    """Cast a HybridBlock's parameters per the AMP policy (reference
    amp.py:convert_hybrid_block)."""
    from ...parallel import _is_norm_stat

    dt = "bfloat16" if str(target_dtype) in ("bfloat16", "bf16") \
        else "float16"
    for name, p in block.collect_params().items():
        if not _is_norm_stat(name) and p._data is not None and \
                jnp.issubdtype(p.data()._data.dtype, jnp.floating):
            p.cast(dt)
    return block
