"""SVRG optimization (reference
python/mxnet/contrib/svrg_optimization/): variance-reduced SGD via a
periodically-refreshed full-batch gradient snapshot."""
from .svrg_module import SVRGModule

__all__ = ["SVRGModule"]
