"""SVRGModule (reference contrib/svrg_optimization/svrg_module.py).

Stochastic Variance Reduced Gradient (Johnson & Zhang 2013): every
``update_freq`` epochs the current weights are snapshotted and the FULL
dataset gradient ``mu`` at the snapshot is computed; each step then
descends along

    g_i(w) - g_i(w_snapshot) + mu

which is an unbiased, variance-reduced gradient estimate.  The
reference implements this as two Modules (main + frozen snapshot) plus
a wrapper optimizer; the same structure is used here over the
TPU-native Module.
"""
from __future__ import annotations

import logging

from ... import ndarray as nd
from ...module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None,
                 update_freq=2):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, work_load_list=work_load_list,
                         fixed_param_names=fixed_param_names,
                         state_names=state_names, group2ctxs=group2ctxs,
                         compression_params=compression_params)
        if not isinstance(update_freq, int) or update_freq <= 0:
            raise ValueError(
                f"update_freq must be a positive int, got {update_freq}")
        self.update_freq = update_freq
        # frozen snapshot executor (the reference's _mod_aux)
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context)
        self._param_dict = None  # mu: full-dataset grads at the snapshot
        self._aux_grads = None   # g_i(w_snapshot) for the current batch

    # ------------------------------------------------------------ bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module,
                     grad_req)
        self._mod_aux.bind(data_shapes, label_shapes, for_training,
                           inputs_need_grad, force_rebind, None,
                           grad_req)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        arg, aux = self.get_params()
        self._mod_aux.init_params(arg_params=arg, aux_params=aux,
                                  force_init=True, allow_missing=True)

    # --------------------------------------------------------- running
    def forward(self, data_batch, is_train=None):
        super().forward(data_batch, is_train)
        if is_train or (is_train is None and self.for_training):
            self._mod_aux.forward(data_batch, is_train=True)

    def backward(self, out_grads=None):
        super().backward(out_grads)
        self._mod_aux.backward(out_grads)
        self._aux_grads = {
            n: self._mod_aux._exec.grad_dict[n].copy()
            for n in self._param_names
            if n in self._mod_aux._exec.grad_dict}

    def update(self):
        """Apply the SVRG-adjusted gradient through the optimizer
        (reference _update_svrg_gradients + Module.update)."""
        if self._param_dict is not None and self._aux_grads is not None:
            for name in self._param_names:
                g = self._exec.grad_dict.get(name)
                if g is None:
                    continue
                g_spec = self._aux_grads.get(name)
                mu = self._param_dict.get(name)
                if g_spec is not None and mu is not None:
                    g._adopt(g._data - g_spec._data + mu._data)
        super().update()

    # -------------------------------------------------------- full grad
    def update_full_grads(self, train_data):
        """Snapshot the current weights into the aux module and compute
        mu = the average gradient over the whole ``train_data``
        (reference update_full_grads)."""
        arg, aux = self.get_params()
        self._mod_aux.init_params(arg_params=arg, aux_params=aux,
                                  force_init=True, allow_missing=True)
        train_data.reset()
        accum = {}
        nbatch = 0
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            for name in self._param_names:
                g = self._mod_aux._exec.grad_dict.get(name)
                if g is None:
                    continue
                if name in accum:
                    accum[name]._adopt(accum[name]._data + g._data)
                else:
                    accum[name] = g.copy()
            nbatch += 1
        self._param_dict = {
            n: nd.NDArray(v._data / max(nbatch, 1))
            for n, v in accum.items()}
        train_data.reset()

    # -------------------------------------------------------------- fit
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        from ... import initializer as init_mod
        from ... import metric as metric_mod

        assert num_epoch is not None, "please specify number of epochs"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer
                         or init_mod.Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                self.forward(data_batch, is_train=True)
                self.backward()
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    from ...callback import BatchEndParam

                    batch_end_callback(BatchEndParam(
                        epoch=epoch, nbatch=nbatch,
                        eval_metric=eval_metric, locals=locals()))
            if epoch_end_callback is not None:
                arg, auxp = self.get_params()
                epoch_end_callback(epoch, self.symbol, arg, auxp)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric
                                 or eval_metric)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
