"""Structural validator for exported models (vendored analog of
onnx.checker.check_model for the schema subset we emit)."""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ._proto import pb

_ITEMSIZE = {pb.TensorProto.FLOAT: 4, pb.TensorProto.DOUBLE: 8,
             pb.TensorProto.FLOAT16: 2, pb.TensorProto.BFLOAT16: 2,
             pb.TensorProto.INT32: 4, pb.TensorProto.INT64: 8,
             pb.TensorProto.INT8: 1, pb.TensorProto.UINT8: 1,
             pb.TensorProto.BOOL: 1}


def check_model(model_or_path):
    """Raise MXNetError on structural problems; returns the parsed
    ModelProto on success."""
    if isinstance(model_or_path, (str, bytes)) and not isinstance(
            model_or_path, pb.ModelProto):
        model = pb.ModelProto()
        if isinstance(model_or_path, str):
            with open(model_or_path, "rb") as f:
                model.ParseFromString(f.read())
        else:
            model.ParseFromString(model_or_path)
    else:
        model = model_or_path

    if model.ir_version < 3:
        raise MXNetError(f"bad ir_version {model.ir_version}")
    if not model.opset_import:
        raise MXNetError("missing opset_import")
    g = model.graph
    if not g.node:
        raise MXNetError("empty graph")

    defined = set()
    for t in g.initializer:
        if not t.name:
            raise MXNetError("unnamed initializer")
        n = 1
        for d in t.dims:
            if d < 0:
                raise MXNetError(f"negative dim in {t.name}")
            n *= d
        itemsize = _ITEMSIZE.get(t.data_type)
        if itemsize is None:
            raise MXNetError(f"{t.name}: unknown data_type {t.data_type}")
        if t.raw_data and len(t.raw_data) != n * itemsize:
            raise MXNetError(
                f"{t.name}: raw_data {len(t.raw_data)}B != "
                f"dims product {n} x itemsize {itemsize}")
        defined.add(t.name)
    for vi in g.input:
        defined.add(vi.name)

    for node in g.node:
        if not node.op_type:
            raise MXNetError("node without op_type")
        for i in node.input:
            if i and i not in defined:
                raise MXNetError(
                    f"node {node.name or node.op_type}: input {i!r} "
                    "not produced by a prior node/initializer/input "
                    "(graph must be topologically sorted)")
        for o in node.output:
            defined.add(o)

    for vi in g.output:
        if vi.name not in defined:
            raise MXNetError(f"graph output {vi.name!r} never produced")
    return model


def check_numpy_roundtrip(arr):
    """Tensor encode/decode self-test used by the test-suite."""
    from .mx2onnx import _tensor
    from .onnx2mx import _to_numpy

    t = _tensor("t", arr)
    back = _to_numpy(t)
    if not onp.array_equal(onp.asarray(arr, back.dtype), back):
        raise MXNetError("tensor roundtrip mismatch")
    return True
