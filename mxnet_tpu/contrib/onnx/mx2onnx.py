"""Symbol → ONNX export.

Reference: python/mxnet/contrib/onnx/mx2onnx/_op_translations.py +
export_onnx.py — a per-op translation table walked over the Symbol's
nnvm JSON graph.  Emits through the vendored IR bindings
(``_proto/onnx_subset.proto``); files are readable by stock onnx.
"""
from __future__ import annotations

import ast
import json

import numpy as onp

from ...base import MXNetError
from ._proto import pb

ONNX_OPSET = 13
_DT = {"float32": pb.TensorProto.FLOAT, "float64": pb.TensorProto.DOUBLE,
       "float16": pb.TensorProto.FLOAT16, "int32": pb.TensorProto.INT32,
       "int64": pb.TensorProto.INT64, "int8": pb.TensorProto.INT8,
       "uint8": pb.TensorProto.UINT8, "bool": pb.TensorProto.BOOL,
       "bfloat16": pb.TensorProto.BFLOAT16}


def _lit(v, default=None):
    """Parse an attrs string back to a python literal."""
    if v is None:
        return default
    if isinstance(v, str):
        try:
            return ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return v
    return v


def _tensor(name, arr):
    t = pb.TensorProto()
    t.name = name
    a = onp.asarray(arr)
    if a.dtype == onp.float64 or str(a.dtype) == "bfloat16":
        # f64: ONNX consumers mostly expect f32; bf16: the importer's
        # numpy decode path has no BFLOAT16 codec, so widen on export
        a = a.astype(onp.float32)
    t.dims.extend(a.shape)
    t.data_type = _DT[str(a.dtype)]
    t.raw_data = a.tobytes()
    return t


def _vinfo(name, shape, dtype="float32", unknown_rank=False):
    vi = pb.ValueInfoProto()
    vi.name = name
    vi.type.tensor_type.elem_type = _DT[dtype]
    if unknown_rank:
        # leave the shape message unset entirely: claiming () would
        # declare a scalar and break strict shape inference downstream
        return vi
    for d in shape:
        dim = vi.type.tensor_type.shape.dim.add()
        if d is None or d == 0:
            dim.dim_param = "N"
        else:
            dim.dim_value = int(d)
    return vi


def _node(op_type, inputs, outputs, name="", **attrs):
    n = pb.NodeProto()
    n.op_type = op_type
    n.input.extend(inputs)
    n.output.extend(outputs)
    n.name = name or outputs[0]
    for k, v in attrs.items():
        if v is None:
            continue
        a = n.attribute.add()
        a.name = k
        if isinstance(v, float):
            a.type = pb.AttributeProto.FLOAT
            a.f = v
        elif isinstance(v, bool):
            a.type = pb.AttributeProto.INT
            a.i = int(v)
        elif isinstance(v, int):
            a.type = pb.AttributeProto.INT
            a.i = v
        elif isinstance(v, str):
            a.type = pb.AttributeProto.STRING
            a.s = v.encode()
        elif isinstance(v, (list, tuple)):
            if v and isinstance(v[0], float):
                a.type = pb.AttributeProto.FLOATS
                a.floats.extend(v)
            else:
                a.type = pb.AttributeProto.INTS
                a.ints.extend(int(x) for x in v)
        else:
            raise MXNetError(f"unsupported attribute value {v!r}")
    return n


# ---------------------------------------------------------- translators
# each: (ctx, name, inputs, attrs) -> list[NodeProto]; ctx carries the
# graph builder state (initializers, fresh-name counter)
class _Ctx:
    def __init__(self, params):
        self.params = params
        self.initializers = []
        self.init_names = set()
        self._n = 0

    def fresh(self, base):
        self._n += 1
        return f"{base}_{self._n}"

    def add_init(self, name, arr):
        if name not in self.init_names:
            self.initializers.append(_tensor(name, arr))
            self.init_names.add(name)
        return name


def _conv(ctx, name, ins, attrs):
    if _lit(attrs.get("layout"), "NCHW") not in (None, "NCHW", "NCW"):
        raise MXNetError("ONNX export requires channel-first layout "
                         "(ONNX Conv is NCHW); rebuild the net without "
                         "layout='NHWC'")
    kernel = _lit(attrs.get("kernel"))
    stride = _lit(attrs.get("stride"), (1,) * len(kernel))
    pad = _lit(attrs.get("pad"), (0,) * len(kernel))
    dilate = _lit(attrs.get("dilate"), (1,) * len(kernel))
    return [_node("Conv", ins, [name], name,
                  kernel_shape=list(kernel), strides=list(stride),
                  pads=list(pad) * 2, dilations=list(dilate),
                  group=int(_lit(attrs.get("num_group"), 1)))]


def _bn(ctx, name, ins, attrs):
    # ins: data, gamma, beta, moving_mean, moving_var
    if _lit(attrs.get("fix_gamma"), False):
        g = ctx.params.get(ins[1])
        shape = g.shape if g is not None else None
        if shape is None:
            raise MXNetError("fix_gamma BatchNorm export needs params")
        ones_name = ctx.fresh(ins[1] + "_fixed")
        ctx.add_init(ones_name, onp.ones(shape, "float32"))
        ins = [ins[0], ones_name] + list(ins[2:])
    return [_node("BatchNormalization", list(ins), [name], name,
                  epsilon=float(_lit(attrs.get("eps"), 1e-3)),
                  momentum=float(_lit(attrs.get("momentum"), 0.9)))]


def _act(ctx, name, ins, attrs):
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = _lit(attrs.get("act_type"), "relu")
    if act not in table:
        raise MXNetError(f"Activation {act} has no ONNX mapping")
    return [_node(table[act], ins, [name], name)]


def _fc(ctx, name, ins, attrs):
    no_bias = _lit(attrs.get("no_bias"), False)
    flatten = _lit(attrs.get("flatten"), True)
    nodes = []
    data = ins[0]
    if flatten:
        flat = ctx.fresh(name + "_flat")
        nodes.append(_node("Flatten", [data], [flat], flat, axis=1))
        data = flat
    gemm_in = [data, ins[1]] + ([] if no_bias else [ins[2]])
    nodes.append(_node("Gemm", gemm_in, [name], name, alpha=1.0, beta=1.0,
                       transA=0, transB=1))
    return nodes


def _pool(ctx, name, ins, attrs):
    ptype = _lit(attrs.get("pool_type"), "max")
    if _lit(attrs.get("global_pool"), False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise MXNetError(f"global {ptype} pool has no ONNX mapping")
        return [_node(op, ins, [name], name)]
    kernel = _lit(attrs.get("kernel"))
    stride = _lit(attrs.get("stride"), (1,) * len(kernel))
    pad = _lit(attrs.get("pad"), (0,) * len(kernel))
    ceil_mode = _lit(attrs.get("pooling_convention"), "valid") == "full"
    op = {"max": "MaxPool", "avg": "AveragePool"}.get(ptype)
    if op is None:
        raise MXNetError(f"pool_type {ptype} has no ONNX mapping")
    kw = dict(kernel_shape=list(kernel), strides=list(stride),
              pads=list(pad) * 2, ceil_mode=int(ceil_mode))
    if op == "AveragePool":
        kw["count_include_pad"] = int(
            _lit(attrs.get("count_include_pad"), True))
    return [_node(op, ins, [name], name, **kw)]


def _softmax(ctx, name, ins, attrs):
    return [_node("Softmax", ins[:1], [name], name,
                  axis=int(_lit(attrs.get("axis"), -1)))]


def _flatten_op(ctx, name, ins, attrs):
    return [_node("Flatten", ins, [name], name, axis=1)]


def _concat(ctx, name, ins, attrs):
    ax = attrs.get("dim", attrs.get("axis"))
    return [_node("Concat", list(ins), [name], name,
                  axis=int(_lit(ax, 1)))]


def _dropout(ctx, name, ins, attrs):
    return [_node("Identity", ins[:1], [name], name)]


def _binary(onnx_op):
    def f(ctx, name, ins, attrs):
        return [_node(onnx_op, list(ins), [name], name)]
    return f


def _clip(ctx, name, ins, attrs):
    lo = ctx.add_init(ctx.fresh(name + "_min"),
                      onp.float32(_lit(attrs.get("a_min"), 0.0)))
    hi = ctx.add_init(ctx.fresh(name + "_max"),
                      onp.float32(_lit(attrs.get("a_max"), 0.0)))
    return [_node("Clip", [ins[0], lo, hi], [name], name)]


def _reshape(ctx, name, ins, attrs):
    shape = _lit(attrs.get("shape"))
    sh = ctx.add_init(ctx.fresh(name + "_shape"),
                      onp.asarray(shape, "int64"))
    return [_node("Reshape", [ins[0], sh], [name], name)]


def _leaky(ctx, name, ins, attrs):
    act = _lit(attrs.get("act_type"), "leaky")
    if act != "leaky":
        raise MXNetError(f"LeakyReLU act_type {act} has no ONNX mapping")
    return [_node("LeakyRelu", ins[:1], [name], name,
                  alpha=float(_lit(attrs.get("slope"), 0.25)))]


_TRANSLATORS = {
    "Convolution": _conv,
    "BatchNorm": _bn,
    "Activation": _act,
    "FullyConnected": _fc,
    "Pooling": _pool,
    "softmax": _softmax,
    "Softmax": _softmax,
    "Flatten": _flatten_op,
    "Concat": _concat,
    "concat": _concat,
    "Dropout": _dropout,
    "elemwise_add": _binary("Add"),
    "broadcast_add": _binary("Add"),
    "elemwise_sub": _binary("Sub"),
    "broadcast_sub": _binary("Sub"),
    "elemwise_mul": _binary("Mul"),
    "broadcast_mul": _binary("Mul"),
    "elemwise_div": _binary("Div"),
    "broadcast_div": _binary("Div"),
    "relu": lambda c, n, i, a: [_node("Relu", i, [n], n)],
    "sigmoid": lambda c, n, i, a: [_node("Sigmoid", i, [n], n)],
    "tanh": lambda c, n, i, a: [_node("Tanh", i, [n], n)],
    "exp": lambda c, n, i, a: [_node("Exp", i, [n], n)],
    "log": lambda c, n, i, a: [_node("Log", i, [n], n)],
    "sqrt": lambda c, n, i, a: [_node("Sqrt", i, [n], n)],
    "clip": _clip,
    "Reshape": _reshape,
    "LeakyReLU": _leaky,
}


def export_model(sym, params, input_shape, input_type="float32",
                 onnx_file_path="model.onnx", verbose=False):
    """Export (Symbol, params) to an ONNX file.

    ``params`` maps parameter name → NDArray (merged arg+aux, the
    reference convention); ``input_shape`` is one shape tuple (or a
    list with one entry) for the single graph input.
    """
    from ...ndarray import NDArray

    if isinstance(input_shape, list):
        if len(input_shape) != 1:
            raise MXNetError("one graph input supported")
        input_shape = input_shape[0]
    params = {k.split(":", 1)[-1]:
              (v.asnumpy() if isinstance(v, NDArray) else onp.asarray(v))
              for k, v in params.items()}

    graph = json.loads(sym.tojson())
    nodes = graph["nodes"]
    heads = graph["heads"]

    ctx = _Ctx(params)
    out_name = {}
    onnx_nodes = []
    graph_inputs = []
    for nid, n in enumerate(nodes):
        op, name = n["op"], n["name"]
        if op == "null":
            out_name[(nid, 0)] = name
            if name in params:
                ctx.add_init(name, params[name])
            else:
                if graph_inputs:
                    raise MXNetError(
                        f"variable {name!r} has no entry in params and "
                        f"{graph_inputs[0].name!r} is already the data "
                        "input — missing/typo'd parameter key?")
                graph_inputs.append(_vinfo(name, input_shape, input_type))
            continue
        ins = [out_name[(i[0], i[1])] for i in n["inputs"]]
        attrs = n.get("attrs", {})
        tr = _TRANSLATORS.get(op)
        if tr is None:
            raise MXNetError(f"op {op!r} has no ONNX translation "
                             "(reference _op_translations.py parity "
                             "covers the model-zoo subset)")
        new_nodes = tr(ctx, name, ins, attrs)
        onnx_nodes.extend(new_nodes)
        nouts = len(new_nodes[-1].output)
        for i in range(nouts):
            out_name[(nid, i)] = new_nodes[-1].output[i]
        if verbose:
            print(f"{op} {name} -> "
                  f"{[nn.op_type for nn in new_nodes]}")

    model = pb.ModelProto()
    model.ir_version = 8
    model.producer_name = "mxnet_tpu"
    model.producer_version = "0.1"
    opset = model.opset_import.add()
    opset.version = ONNX_OPSET
    g = model.graph
    g.name = "mxnet_tpu_graph"
    g.node.extend(onnx_nodes)
    g.initializer.extend(ctx.initializers)
    g.input.extend(graph_inputs)
    for (nid, i) in [(h[0], h[1]) for h in heads]:
        g.output.extend([_vinfo(out_name[(nid, i)], (),
                                unknown_rank=True)])
    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path
