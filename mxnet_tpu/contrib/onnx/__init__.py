"""ONNX import/export (reference: python/mxnet/contrib/onnx/ —
mx2onnx export_model + onnx2mx import_model).

This environment has no ``onnx`` package, so the IR schema subset is
vendored (``_proto/onnx_subset.proto``, field-number-faithful to the
public spec) and compiled with protoc: exported files are readable by
stock onnx and stock-onnx files (for the supported op set) import here.
Covered ops: Conv, BatchNormalization, Gemm, MaxPool/AveragePool +
global variants, Relu/Sigmoid/Tanh/Softplus/LeakyRelu, Softmax,
Flatten, Concat, Add/Sub/Mul/Div, Clip, Reshape, Dropout(->Identity),
Exp/Log/Sqrt — the reference _op_translations.py model-zoo subset.
"""
from __future__ import annotations

from .checker import check_model  # noqa: F401
from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import get_model_metadata, import_model  # noqa: F401

__all__ = ["export_model", "import_model", "get_model_metadata",
           "check_model"]
