"""ONNX import/export (reference: python/mxnet/contrib/onnx/ —
mx2onnx export_model + onnx2mx import_model).

Environment triage: the ``onnx`` package is not installed in this
zero-egress image, and emitting/parsing ONNX protobufs without it would
mean vendoring the schema.  The API surface is preserved and fails
fast with an actionable error; the native interchange formats —
Symbol JSON + bit-compatible ``.params`` (reference formats, round-trip
tested) — cover save/load/deploy within the framework.
"""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["export_model", "import_model", "get_model_metadata"]

_MSG = ("the 'onnx' python package is not available in this "
        "environment; install onnx to use contrib.onnx, or use the "
        "native interchange (Symbol.tojson + nd.save .params, loadable "
        "via SymbolBlock.imports / Module.load)")


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False):
    """Reference: contrib/onnx/mx2onnx/export_model.py."""
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise MXNetError(_MSG) from e
    raise MXNetError("onnx export backend not implemented")


def import_model(model_file):
    """Reference: contrib/onnx/onnx2mx/import_model.py."""
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise MXNetError(_MSG) from e
    raise MXNetError("onnx import backend not implemented")


def get_model_metadata(model_file):
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise MXNetError(_MSG) from e
    raise MXNetError("onnx import backend not implemented")
