"""Vendored ONNX IR protobuf bindings.

``onnx_subset.proto`` is a field-number-faithful subset of the public
ONNX schema (Apache-2.0); ``onnx_subset_pb2.py`` is protoc output from
it.  Files serialized here parse with stock ``onnx`` and vice versa
(for the message subset we use).
"""
from . import onnx_subset_pb2 as pb  # noqa: F401
