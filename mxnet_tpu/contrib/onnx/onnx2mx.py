"""ONNX → Symbol import.

Reference: python/mxnet/contrib/onnx/onnx2mx/import_model.py +
_import_helper.py op map.  Parses through the vendored IR bindings, so
stock-onnx files (for the supported op subset) load without the onnx
package installed.
"""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ._proto import pb

_NP_DT = {pb.TensorProto.FLOAT: onp.float32,
          pb.TensorProto.DOUBLE: onp.float64,
          pb.TensorProto.FLOAT16: onp.float16,
          pb.TensorProto.INT32: onp.int32,
          pb.TensorProto.INT64: onp.int64,
          pb.TensorProto.INT8: onp.int8,
          pb.TensorProto.UINT8: onp.uint8,
          pb.TensorProto.UINT32: onp.uint32,
          pb.TensorProto.UINT64: onp.uint64,
          pb.TensorProto.BOOL: onp.bool_}


def _to_numpy(t):
    dt = _NP_DT.get(t.data_type)
    if dt is None:
        raise MXNetError(f"unsupported tensor data_type {t.data_type}")
    if t.raw_data:
        a = onp.frombuffer(t.raw_data, dtype=dt)
    elif t.float_data:
        a = onp.asarray(t.float_data, dtype=dt)
    elif t.double_data:
        a = onp.asarray(t.double_data, dtype=dt)
    elif t.int64_data:
        a = onp.asarray(t.int64_data, dtype=dt)
    elif t.uint64_data:
        a = onp.asarray(t.uint64_data, dtype=dt)
    elif t.int32_data:
        if t.data_type == pb.TensorProto.FLOAT16:
            # spec: fp16 values are uint16 BIT PATTERNS in int32_data
            a = onp.asarray(t.int32_data, dtype=onp.uint16).view(
                onp.float16)
        else:
            a = onp.asarray(t.int32_data, dtype=dt)
    else:
        a = onp.zeros(0, dtype=dt)
    return a.reshape(tuple(t.dims))


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == pb.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == pb.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == pb.AttributeProto.INTS:
            out[a.name] = tuple(a.ints)
        elif a.type == pb.AttributeProto.FLOATS:
            out[a.name] = tuple(a.floats)
        elif a.type == pb.AttributeProto.TENSOR:
            out[a.name] = _to_numpy(a.t)
    return out


def _pads2(att, nd_):
    p = att.get("pads")
    if p is None:
        return (0,) * nd_
    begin, end = p[:nd_], p[nd_:]
    if tuple(begin) != tuple(end):
        raise MXNetError(f"asymmetric pads {p} unsupported on import")
    return tuple(begin)


def import_model(model_file):
    """Returns (sym, arg_params, aux_params) — reference
    import_model.py signature."""
    from ... import symbol as sym_mod
    from ...ndarray import array as nd_array

    model = pb.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph
    opset = max((o.version for o in model.opset_import
                 if o.domain in ("", "ai.onnx")), default=13)

    inits = {t.name: _to_numpy(t) for t in g.initializer}
    env = {}
    aux_names = set()

    for vi in g.input:
        if vi.name not in inits:
            env[vi.name] = sym_mod.var(vi.name)
    for name in inits:
        env[name] = sym_mod.var(name)

    def n_in(node, i):
        return env[node.input[i]]

    def _init_of(node, i, what):
        """Initializer tensor for input slot i, with clean errors for
        the legal-but-unsupported cases (empty-string optional inputs,
        weights arriving as graph inputs instead of initializers)."""
        if i >= len(node.input) or not node.input[i]:
            return None
        name = node.input[i]
        if name not in inits:
            raise MXNetError(
                f"{node.op_type} {node.name or ''}: {what} "
                f"({name!r}) is not a graph initializer; dynamic "
                "weights/bounds are not supported on import")
        return inits[name]

    for node in g.node:
        op = node.op_type
        att = _attrs(node)
        outs = list(node.output)
        if op == "Conv":
            k = att["kernel_shape"]
            nd_ = len(k)
            ins = [n_in(node, i) for i in range(len(node.input))]
            out = sym_mod.Convolution(
                *ins, kernel=tuple(k),
                stride=tuple(att.get("strides", (1,) * nd_)),
                dilate=tuple(att.get("dilations", (1,) * nd_)),
                pad=_pads2(att, nd_),
                num_filter=int(_init_of(node, 1, "weight").shape[0]),
                num_group=int(att.get("group", 1)),
                no_bias=len(node.input) < 3, name=node.name)
        elif op == "BatchNormalization":
            ins = [n_in(node, i) for i in range(5)]
            aux_names.update(node.input[3:5])
            out = sym_mod.BatchNorm(
                *ins, eps=float(att.get("epsilon", 1e-5)),
                momentum=float(att.get("momentum", 0.9)),
                fix_gamma=False, name=node.name)
        elif op == "Gemm":
            if att.get("transA", 0) or not att.get("transB", 0):
                raise MXNetError("only Gemm(transA=0, transB=1) imports")
            ins = [n_in(node, i) for i in range(len(node.input))]
            out = sym_mod.FullyConnected(
                *ins, num_hidden=int(_init_of(node, 1,
                                              "weight").shape[0]),
                no_bias=len(node.input) < 3, flatten=False,
                name=node.name)
        elif op in ("MaxPool", "AveragePool"):
            k = att["kernel_shape"]
            nd_ = len(k)
            out = sym_mod.Pooling(
                n_in(node, 0), kernel=tuple(k),
                stride=tuple(att.get("strides", (1,) * nd_)),
                pad=_pads2(att, nd_),
                pool_type="max" if op == "MaxPool" else "avg",
                pooling_convention="full" if att.get("ceil_mode")
                else "valid",
                # ONNX operator default EXCLUDES padding (spec: 0);
                # the exporter always writes the attribute, so only
                # foreign models hit this default
                count_include_pad=bool(att.get("count_include_pad", 0)),
                name=node.name)
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            out = sym_mod.Pooling(
                n_in(node, 0), global_pool=True,
                pool_type="max" if op == "GlobalMaxPool" else "avg",
                kernel=(1, 1), name=node.name)
        elif op == "Flatten":
            out = sym_mod.Flatten(n_in(node, 0), name=node.name)
        elif op == "Relu":
            out = sym_mod.Activation(n_in(node, 0), act_type="relu",
                                     name=node.name)
        elif op == "Sigmoid":
            out = sym_mod.Activation(n_in(node, 0), act_type="sigmoid",
                                     name=node.name)
        elif op == "Tanh":
            out = sym_mod.Activation(n_in(node, 0), act_type="tanh",
                                     name=node.name)
        elif op == "Softplus":
            out = sym_mod.Activation(n_in(node, 0), act_type="softrelu",
                                     name=node.name)
        elif op == "LeakyRelu":
            out = sym_mod.LeakyReLU(n_in(node, 0), act_type="leaky",
                                    slope=float(att.get("alpha", 0.01)),
                                    name=node.name)
        elif op == "Softmax":
            if opset >= 13:
                out = sym_mod.softmax(n_in(node, 0),
                                      axis=int(att.get("axis", -1)),
                                      name=node.name)
            else:
                # opset <13: coerce-to-2D — flatten dims from `axis`
                # and normalize over them jointly, then restore shape
                axis = int(att.get("axis", 1))
                d = n_in(node, 0)
                if axis == -1:
                    # flattening from the last axis is the identity:
                    # plain last-axis softmax
                    out = sym_mod.softmax(d, axis=-1, name=node.name)
                elif axis < 0:
                    raise MXNetError(
                        f"opset<13 Softmax with negative axis {axis} "
                        "needs the input rank, which import does not "
                        "know; re-export at opset>=13")
                else:
                    flat = sym_mod.Reshape(
                        d, shape=(0,) * axis + (-1,))
                    sm = sym_mod.softmax(flat, axis=-1)
                    out = sym_mod.reshape_like(sm, d, name=node.name)
        elif op == "Concat":
            ins = [n_in(node, i) for i in range(len(node.input))]
            out = sym_mod.Concat(*ins, num_args=len(ins),
                                 dim=int(att.get("axis", 1)),
                                 name=node.name)
        elif op in ("Add", "Sub", "Mul", "Div"):
            mxop = {"Add": "broadcast_add", "Sub": "broadcast_sub",
                    "Mul": "broadcast_mul", "Div": "broadcast_div"}[op]
            out = getattr(sym_mod, mxop)(n_in(node, 0), n_in(node, 1),
                                         name=node.name)
        elif op == "Identity":
            out = n_in(node, 0)
        elif op == "Clip":
            # opset <11 carries bounds as min/max attributes
            lo_t = _init_of(node, 1, "min bound")
            hi_t = _init_of(node, 2, "max bound")
            lo = float(lo_t) if lo_t is not None \
                else float(att.get("min", -onp.inf))
            hi = float(hi_t) if hi_t is not None \
                else float(att.get("max", onp.inf))
            out = sym_mod.clip(n_in(node, 0), a_min=lo, a_max=hi,
                               name=node.name)
        elif op == "Reshape":
            shape = tuple(int(x) for x in inits[node.input[1]])
            out = sym_mod.Reshape(n_in(node, 0), shape=shape,
                                  name=node.name)
        elif op in ("Exp", "Log", "Sqrt"):
            out = getattr(sym_mod, op.lower())(n_in(node, 0),
                                               name=node.name)
        else:
            raise MXNetError(f"ONNX op {op!r} has no import mapping")
        if len(outs) == 1:
            env[outs[0]] = out
        else:
            for i, o in enumerate(outs):
                env[o] = out[i]

    out_syms = [env[o.name] for o in g.output]
    sym = out_syms[0] if len(out_syms) == 1 \
        else sym_mod.Group(out_syms)
    arg_params, aux_params = {}, {}
    for name, a in inits.items():
        # Clip/Reshape constants etc. are folded into attrs, but keep
        # them out of params only if some symbol references them
        target = aux_params if name in aux_names else arg_params
        target[name] = nd_array(a)
    used = set(sym.list_arguments()) | set(sym.list_auxiliary_states()) \
        if hasattr(sym, "list_auxiliary_states") \
        else set(sym.list_arguments())
    arg_params = {k: v for k, v in arg_params.items() if k in used}
    aux_params = {k: v for k, v in aux_params.items() if k in used}
    return sym, arg_params, aux_params


def get_model_metadata(model_file):
    """Reference: onnx2mx/import_model.py get_model_metadata."""
    model = pb.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph
    inits = {t.name for t in g.initializer}

    def shapes(vis):
        out = []
        for vi in vis:
            if vi.name in inits:
                continue
            dims = tuple(
                d.dim_value if d.HasField("dim_value") else d.dim_param
                for d in vi.type.tensor_type.shape.dim)
            out.append((vi.name, dims))
        return out

    return {"input_tensor_data": shapes(g.input),
            "output_tensor_data": shapes(g.output)}
