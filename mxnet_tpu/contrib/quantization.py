"""Model quantization driver (reference:
python/mxnet/contrib/quantization.py:87 quantize_model with
minmax/entropy calibration :231).

TPU-native scope: INT8 post-training quantization of gluon networks —
Dense/Conv2D layers swap to quantized blocks (int8 weights + calibrated
activation ranges feeding the _contrib_quantized_* ops); everything
else stays float, with quantize/dequantize at the boundaries, the same
topology the reference's graph pass produces.
"""
from __future__ import annotations

import numpy as onp

from .. import ndarray as nd
from ..base import MXNetError
from ..gluon.block import HybridBlock

__all__ = ["quantize_net", "calib_minmax", "calib_entropy",
           "QuantizedDense"]


def calib_minmax(samples):
    """naive calibration: global min/max (reference calib_mode='naive')."""
    mn = min(float(s.min().asnumpy() if hasattr(s, "asnumpy")
                   else onp.min(s)) for s in samples)
    mx = max(float(s.max().asnumpy() if hasattr(s, "asnumpy")
                   else onp.max(s)) for s in samples)
    return mn, mx


def calib_entropy(samples, num_bins=1001, num_quantized_bins=255):
    """KL-divergence threshold calibration (reference
    quantization.py:231 _get_optimal_threshold, simplified sweep)."""
    arr = onp.concatenate([
        onp.abs(onp.asarray(s.asnumpy() if hasattr(s, "asnumpy") else s)
                ).ravel() for s in samples])
    amax = float(arr.max()) if arr.size else 1.0
    if amax == 0:
        return -1.0, 1.0
    hist, edges = onp.histogram(arr, bins=num_bins, range=(0, amax))
    best_kl, best_t = onp.inf, amax
    for stop in range(num_quantized_bins, num_bins + 1, 50):
        t = edges[stop]
        p = hist[:stop].astype("float64").copy()
        p[-1] += hist[stop:].sum()  # clip outliers into the last bin
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = stop / num_quantized_bins
        q = onp.zeros_like(p)
        for i in range(num_quantized_bins):
            lo = int(i * factor)
            hi = max(int((i + 1) * factor), lo + 1)
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = onp.where(chunk > 0, chunk.sum() / nz, 0)
        pn = p / p.sum()
        qn = q / max(q.sum(), 1e-12)
        mask = pn > 0
        kl = float((pn[mask] * onp.log(
            pn[mask] / onp.maximum(qn[mask], 1e-12))).sum())
        if kl < best_kl:
            best_kl, best_t = kl, t
    return -best_t, best_t


class QuantizedDense(HybridBlock):
    """INT8 Dense: calibrated input range + int8 weights feeding
    _contrib_quantized_fully_connected, dequantized output."""

    def __init__(self, dense, act_range, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        w = dense.weight.data()
        b = dense.bias.data() if dense.bias is not None else None
        self._units = w.shape[0]
        wq, wmin, wmax = nd.invoke("_contrib_quantize_v2", [w])
        self._wq, self._wmin, self._wmax = wq, wmin, wmax
        if b is not None:
            bq, bmin, bmax = nd.invoke("_contrib_quantize_v2", [b])
        else:
            bq = nd.zeros((self._units,)).astype("int8")
            bmin, bmax = nd.array([-1.0]), nd.array([1.0])
        self._bq, self._bmin, self._bmax = bq, bmin, bmax
        self._no_bias = b is None
        self._amin, self._amax = act_range
        self._act = getattr(dense, "act", None)  # keep fused activation

    def hybrid_forward(self, F, x):
        xq, xmin, xmax = nd.invoke(
            "_contrib_quantize_v2", [x],
            min_calib_range=self._amin, max_calib_range=self._amax)
        acc, omin, omax = nd.invoke(
            "_contrib_quantized_fully_connected",
            [xq, self._wq, self._bq, xmin, xmax, self._wmin, self._wmax,
             self._bmin, self._bmax],
            num_hidden=self._units, no_bias=self._no_bias)
        out = nd.invoke("_contrib_dequantize", [acc, omin, omax])
        return self._act(out) if self._act is not None else out


def quantize_net(net, calib_data, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=()):
    """Post-training quantize a gluon net's Dense layers in place
    (reference quantize_model, gluon flavor).

    calib_data: iterable of input batches used to record per-layer
    activation ranges.  Returns the modified net.
    """
    from ..gluon.nn import Dense

    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported")
    calib = calib_minmax if calib_mode == "naive" else calib_entropy
    if calib_mode not in ("naive", "entropy"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")

    # hybridized (jit-cached) forwards bypass child hooks: run the
    # calibration passes eagerly, restoring hybridization after
    hybrid_states = []

    def _collect_hybrid(block):
        if getattr(block, "_active", False):
            hybrid_states.append(block)
        for child in block._children.values():
            _collect_hybrid(child)

    _collect_hybrid(net)
    for b in hybrid_states:
        b.hybridize(False)

    # record per-layer input activations via forward hooks
    taps: dict[str, list] = {}
    handles = []

    def _walk(block):
        for name, child in block._children.items():
            if isinstance(child, Dense) and child.name not in \
                    exclude_layers:
                taps.setdefault(child.name, [])

                def hook(blk, inputs, _tap=taps[child.name]):
                    _tap.append(inputs[0])

                handles.append(child.register_forward_pre_hook(hook))
            else:
                _walk(child)

    _walk(net)
    for batch in calib_data:
        net(batch if isinstance(batch, nd.NDArray) else nd.array(batch))
    for h in handles:
        h.detach()

    def _swap(block):
        for name, child in list(block._children.items()):
            if isinstance(child, Dense) and child.name in taps and \
                    taps[child.name]:
                rng = calib(taps[child.name])
                qd = QuantizedDense(child, rng)
                block._children[name] = qd
                # attribute-style blocks (self.fc = Dense(...)) resolve
                # children through __dict__, not _children — swap both
                for attr, val in list(vars(block).items()):
                    if val is child:
                        object.__setattr__(block, attr, qd)
            else:
                _swap(child)

    _swap(net)
    for b in hybrid_states:
        b.hybridize(True)
    return net
